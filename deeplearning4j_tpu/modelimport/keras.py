"""Keras 1.x model import (reference ``deeplearning4j-modelimport``:
``keras/Model.java:58`` importSequentialModel / ``:78``
importFunctionalApiModel / ``:148`` config+weights variants;
``ModelConfiguration.java`` layer-dict mapping;
``LayerConfiguration.java:19-43`` property vocabulary). HDF5 is read
with h5py (the reference goes through JavaCPP hdf5 presets).

Supported layers mirror the reference's ``buildLayer`` switch: Dense /
TimeDistributedDense, LSTM, Convolution2D, MaxPooling2D, Flatten
(skipped — our InputType machinery inserts the reshape), plus the
merge passes for Dropout (folded into the following layer) and
Activation (folded into the preceding layer). Embedding is additionally
supported. Divergences from the reference, on purpose:

- the final Dense becomes an OutputLayer with a loss inferred from its
  activation (softmax→MCXENT, sigmoid→XENT, else MSE) so the imported
  model is trainable; the reference leaves it a plain DenseLayer.
- Theano-ordered conv kernels are already [out, in, kh, kw] and are
  used as-is (the reference permutes them — ``Model.java:383`` — which
  scrambles correct Keras 1.x Theano weights).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration


class IncompatibleKerasConfigurationException(ValueError):
    """Reference ``IncompatibleKerasConfigurationException.java``."""


_ACTIVATION_MAP = {
    "linear": "identity",
    "relu": "relu",
    "sigmoid": "sigmoid",
    "hard_sigmoid": "hardsigmoid",
    "tanh": "tanh",
    "softmax": "softmax",
    "softplus": "softplus",
    "softsign": "softsign",
}

_INIT_MAP = {
    "uniform": "UNIFORM",
    "zero": "ZERO",
    "glorot_normal": "XAVIER",
    "glorot_uniform": "XAVIER_UNIFORM",
    "he_normal": "RELU",
    "he_uniform": "RELU_UNIFORM",
    "lecun_uniform": "UNIFORM",
    "normal": "NORMAL",
}


def _map_activation(name: Optional[str]) -> str:
    if name is None:
        return "identity"
    if name not in _ACTIVATION_MAP:
        raise IncompatibleKerasConfigurationException(
            f"unsupported Keras activation {name!r}"
        )
    return _ACTIVATION_MAP[name]


def _map_init(name: Optional[str]) -> str:
    # unknown inits fall back to XAVIER, like the reference
    # (LayerConfiguration.mapWeightInitialization)
    return _INIT_MAP.get(name or "", "XAVIER")


def _reg(cfg: dict, key: str) -> Tuple[float, float]:
    reg = cfg.get(key) or {}
    return float(reg.get("l1", 0.0) or 0.0), float(reg.get("l2", 0.0)
                                                  or 0.0)


def _infer_loss(activation: str) -> str:
    return {"softmax": "MCXENT", "sigmoid": "XENT"}.get(activation, "MSE")


# ---------------------------------------------------------------------------
# Config import
# ---------------------------------------------------------------------------


def _merge_passes(layer_dicts: List[dict]) -> List[dict]:
    """First pass of ``importSequentialModelConfig``: fold Dropout into
    the next layer, Activation into the previous layer, drop Flatten."""
    merged: List[dict] = []
    pending_dropout = 0.0
    # a dropped leading Dropout/Flatten may carry the model's input
    # shape — hoist it onto the next kept layer instead of losing it
    pending_input: dict = {}
    for entry in layer_dicts:
        cls = entry["class_name"]
        cfg = dict(entry.get("config", {}))
        cfg["keras_class"] = cls
        if cls in ("Dropout", "Flatten"):
            if not merged:
                for k in ("batch_input_shape", "input_shape",
                          "dim_ordering"):
                    if cfg.get(k) is not None and k not in pending_input:
                        pending_input[k] = cfg[k]
            if cls == "Dropout":
                pending_dropout = 1.0 - (1.0 - pending_dropout) * (
                    1.0 - float(cfg.get("p", 0.0))
                )
            # Flatten: our InputType shape inference inserts the
            # CNN→FF reshape
            continue
        if cls == "Activation":
            if not merged:
                raise IncompatibleKerasConfigurationException(
                    "Activation layer found with no preceding layer"
                )
            merged[-1]["activation"] = cfg.get("activation")
            continue
        if pending_dropout > 0:
            old = float(cfg.get("dropout", 0.0) or 0.0)
            cfg["dropout"] = 1.0 - (1.0 - pending_dropout) * (1.0 - old)
            pending_dropout = 0.0
        if not merged and pending_input:
            for k, v in pending_input.items():
                if cfg.get(k) is None:
                    cfg[k] = v
            pending_input = {}
        merged.append(cfg)
    return merged


def _build_layer(cfg: dict, is_output: bool):
    """``LayerConfiguration.buildLayer`` analog — returns a LayerSpec
    or None for structural layers."""
    import dataclasses

    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer,
        DenseLayer,
        EmbeddingLayer,
        GravesLSTM,
        OutputLayer,
        SubsamplingLayer,
    )

    cls = cfg["keras_class"]
    name = cfg.get("name", "")
    act = _map_activation(cfg.get("activation"))
    init = _map_init(cfg.get("init"))
    l1, l2 = _reg(cfg, "W_regularizer")
    dropout = float(cfg.get("dropout", 0.0) or 0.0)

    if cls in ("Dense", "TimeDistributedDense"):
        if is_output:
            return OutputLayer(
                name=name, n_out=int(cfg["output_dim"]), activation=act,
                weight_init=init, dropout=dropout, l1=l1, l2=l2,
                loss=_infer_loss(act),
            )
        return DenseLayer(
            name=name, n_out=int(cfg["output_dim"]), activation=act,
            weight_init=init, dropout=dropout, l1=l1, l2=l2,
        )
    if cls == "LSTM":
        dropout_w = float(cfg.get("dropout_W", 0.0) or 0.0)
        return GravesLSTM(
            name=name, n_out=int(cfg["output_dim"]),
            activation=act if cfg.get("activation") else "tanh",
            gate_activation=_map_activation(
                cfg.get("inner_activation", "hard_sigmoid")
            ),
            forget_gate_bias_init=(
                1.0 if cfg.get("forget_bias_init", "one") == "one" else 0.0
            ),
            weight_init=init, dropout=dropout_w, l1=l1, l2=l2,
            peephole=False,  # Keras LSTMs have no peepholes
        )
    if cls == "Convolution2D":
        stride = cfg.get("subsample", [1, 1])
        border = cfg.get("border_mode", "valid")
        if border not in ("valid", "same"):
            raise IncompatibleKerasConfigurationException(
                f"unsupported border_mode {border!r}"
            )
        kh, kw = int(cfg["nb_row"]), int(cfg["nb_col"])
        padding = (kh // 2, kw // 2) if border == "same" else (0, 0)
        return ConvolutionLayer(
            name=name, n_out=int(cfg["nb_filter"]),
            kernel_size=(kh, kw),
            stride=(int(stride[0]), int(stride[1])), padding=padding,
            activation=act, weight_init=init, dropout=dropout,
            l1=l1, l2=l2,
        )
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        pool = cfg.get("pool_size", [2, 2])
        stride = cfg.get("strides") or pool
        return SubsamplingLayer(
            name=name,
            pooling_type="MAX" if cls == "MaxPooling2D" else "AVG",
            kernel_size=(int(pool[0]), int(pool[1])),
            stride=(int(stride[0]), int(stride[1])),
        )
    if cls == "Embedding":
        return EmbeddingLayer(
            name=name, n_in=int(cfg["input_dim"]),
            n_out=int(cfg["output_dim"]), weight_init=init,
        )
    raise IncompatibleKerasConfigurationException(
        f"Unsupported keras layer type {cls}"
    )


def import_sequential_model_config(config_json: str):
    """Keras Sequential to_json() → MultiLayerConfiguration (reference
    ``ModelConfiguration.importSequentialModelConfig``)."""
    keras = json.loads(config_json)
    if keras.get("class_name") != "Sequential":
        raise IncompatibleKerasConfigurationException(
            f'Expected "Sequential" model config, found '
            f'{keras.get("class_name")!r}'
        )
    layer_dicts = keras.get("config", [])
    merged = _merge_passes(layer_dicts)

    batch_input_shape = None
    dim_ordering = None
    is_recurrent = is_conv = False
    built = []
    for i, cfg in enumerate(merged):
        if "batch_input_shape" in cfg:
            if i > 0:
                raise IncompatibleKerasConfigurationException(
                    'Non-input layer should not specify '
                    '"batch_input_shape"'
                )
            batch_input_shape = cfg["batch_input_shape"]
        elif i == 0:
            raise IncompatibleKerasConfigurationException(
                'Input layer must specify "batch_input_shape"'
            )
        if "dim_ordering" in cfg:
            do = cfg["dim_ordering"]
            if do not in ("th", "tf"):
                raise IncompatibleKerasConfigurationException(
                    f"Unknown Keras backend {do!r}"
                )
            if dim_ordering is not None and do != dim_ordering:
                raise IncompatibleKerasConfigurationException(
                    "Found layers with conflicting Keras backends"
                )
            dim_ordering = do
        layer = _build_layer(cfg, is_output=(i == len(merged) - 1))
        if layer is None:
            continue
        from deeplearning4j_tpu.nn.layers import (
            ConvolutionLayer as _Conv,
            GravesLSTM as _Lstm,
        )
        is_recurrent |= isinstance(layer, _Lstm)
        is_conv |= isinstance(layer, _Conv)
        built.append(layer)

    builder = NeuralNetConfiguration.Builder().list()
    for layer in built:
        builder.layer(layer)
    if is_recurrent and is_conv:
        raise IncompatibleKerasConfigurationException(
            "Recurrent convolutional architecture not supported"
        )
    if is_recurrent:
        builder.set_input_type(InputType.recurrent(
            int(batch_input_shape[2])
        ))
        if batch_input_shape[1] is not None:
            seq = int(batch_input_shape[1])
            builder.t_bptt_forward_length(seq)
            builder.t_bptt_backward_length(seq)
    elif is_conv:
        if dim_ordering == "tf":
            h, w, c = batch_input_shape[1:4]
        else:
            c, h, w = batch_input_shape[1:4]
        builder.set_input_type(
            InputType.convolutional(int(h), int(w), int(c))
        )
    else:
        builder.set_input_type(InputType.feed_forward(
            int(batch_input_shape[-1])
        ))
    return builder.build()


def import_functional_api_config(config_json: str):
    """Functional-API config import — not implemented at this version,
    matching the reference (``Model.java:229``
    ``UnsupportedOperationException``)."""
    raise NotImplementedError(
        "Keras Functional API models are not supported (the reference "
        "throws UnsupportedOperationException at this version)"
    )


# ---------------------------------------------------------------------------
# Weight import
# ---------------------------------------------------------------------------


def _read_weights_h5(group) -> Dict[str, Dict[str, np.ndarray]]:
    """Walk the HDF5 group tree collecting datasets into
    {layer: {param: array}} (reference ``readWeightsFromHdf5``).
    Handles both naming styles: '<layer>_<param>' dataset names and
    'param_N' datasets nested under a layer group."""
    import h5py

    weights: Dict[str, Dict[str, np.ndarray]] = {}

    def visit(name, obj):
        if not isinstance(obj, h5py.Dataset):
            return
        arr = np.asarray(obj[()], np.float32)
        parts = name.split("/")
        dsname = parts[-1]
        # strip TensorFlow's ":0" suffix
        if ":" in dsname:
            dsname = dsname.split(":")[0]
        parent = parts[-2] if len(parts) > 1 else ""
        if dsname.startswith("param_"):
            layer, param = parent or dsname, dsname
        elif parent and dsname.startswith(parent + "_"):
            # Keras layout: group per layer, datasets named
            # "<layer>_<param>" — covers multi-token params like the
            # LSTM's "lstm_1_W_i"
            layer, param = parent, dsname[len(parent) + 1:]
        else:
            # flat layout: "dense_1_W" → layer "dense_1", param "W"
            toks = dsname.split("_")
            layer = "_".join(toks[:-1]) if len(toks) > 1 else (
                parent or dsname
            )
            param = toks[-1]
        weights.setdefault(layer, {})[param] = arr

    group.visititems(visit)
    return weights


def _lstm_pack(w: Dict[str, np.ndarray]):
    """Keras 1.x per-gate LSTM arrays (W_i/U_i/b_i, W_c.., W_f.., W_o..)
    → our fused [in,4n]/[n,4n]/[4n] in i,f,o,g gate order (g = Keras
    'c' cell candidate)."""
    order = ("i", "f", "o", "c")
    W = np.concatenate([w[f"W_{g}"] for g in order], axis=1)
    RW = np.concatenate([w[f"U_{g}"] for g in order], axis=1)
    b = np.concatenate([w[f"b_{g}"] for g in order])
    return W, RW, b


def _set_model_weights(net, weights: Dict[str, Dict[str, np.ndarray]],
                       backend: str) -> None:
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer,
        GravesLSTM,
    )

    for lname, params in weights.items():
        if lname not in net.params:
            raise IncompatibleKerasConfigurationException(
                f"weights for unknown layer {lname!r}; model layers: "
                f"{list(net.params)}"
            )
        idx = net.layer_names.index(lname)
        layer = net.conf.layers[idx]
        new = dict(net.params[lname])
        gate_keys = {f"{m}_{g}" for m in ("W", "U", "b")
                     for g in ("i", "f", "c", "o")}
        if isinstance(layer, GravesLSTM) and gate_keys <= set(params):
            W, RW, b = _lstm_pack(params)
            new["W"] = jnp.asarray(W)
            new["RW"] = jnp.asarray(RW)
            new["b"] = jnp.asarray(b)
            net.params[lname] = new
            continue
        for pname, arr in params.items():
            if isinstance(layer, ConvolutionLayer) and pname == "W":
                if backend == "tf":
                    # [kh, kw, in, out] → [out, in, kh, kw]
                    arr = np.transpose(arr, (3, 2, 0, 1))
                # th already stores [out, in, kh, kw]
            if pname not in new:
                raise IncompatibleKerasConfigurationException(
                    f"unknown param {pname!r} for layer {lname!r} "
                    f"(has {list(new)})"
                )
            if new[pname].shape != arr.shape:
                raise IncompatibleKerasConfigurationException(
                    f"shape mismatch for {lname}.{pname}: model "
                    f"{tuple(new[pname].shape)} vs weights {arr.shape}"
                )
            new[pname] = jnp.asarray(arr)
        net.params[lname] = new


def _extract_backend(config_json: str) -> str:
    keras = json.loads(config_json)
    backend = keras.get("keras_backend")
    if backend:
        return backend
    for entry in keras.get("config", []):
        do = entry.get("config", {}).get("dim_ordering")
        if do:
            return do
    return "th"


def import_sequential_model(model_or_config_path: str,
                            weights_path: Optional[str] = None):
    """Import a Keras Sequential model into a MultiLayerNetwork
    (reference ``Model.importSequentialModel`` — one-arg form reads a
    combined save_model() HDF5 with a 'model_config' attribute +
    '/model_weights'; two-arg form takes to_json() config +
    save_weights() HDF5)."""
    import h5py

    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if weights_path is None:
        with h5py.File(model_or_config_path, "r") as f:
            raw = f.attrs.get("model_config")
            if raw is None:
                raise IncompatibleKerasConfigurationException(
                    f"{model_or_config_path!r} has no 'model_config' "
                    "attribute; for a weights-only file pass the config "
                    "JSON path as the first argument"
                )
            config_json = (
                raw.decode() if isinstance(raw, bytes) else str(raw)
            )
            group = (
                f["model_weights"] if "model_weights" in f else f["/"]
            )
            weights = _read_weights_h5(group)
    else:
        with open(model_or_config_path, "r", encoding="utf-8") as fh:
            config_json = fh.read()
        with h5py.File(weights_path, "r") as f:
            weights = _read_weights_h5(f["/"])
    conf = import_sequential_model_config(config_json)
    net = MultiLayerNetwork(conf).init()
    _set_model_weights(net, weights, _extract_backend(config_json))
    return net


def import_functional_api_model(model_path: str,
                                weights_path: Optional[str] = None):
    """Reference ``Model.importFunctionalApiModel`` — throws at this
    version (``Model.java:229``)."""
    raise NotImplementedError(
        "Keras Functional API models are not supported (matches the "
        "reference, which throws UnsupportedOperationException)"
    )
