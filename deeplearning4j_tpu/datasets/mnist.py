"""MNIST (reference: ``datasets/mnist/MnistManager.java`` IDX parsing +
``MnistDataFetcher`` download/cache + ``MnistDataSetIterator``).

The reference downloads MNIST at first use. This build environment has
no egress, so resolution order is:
1. ``DL4J_TPU_MNIST_DIR`` env var or ``data_dir`` argument pointing at
   the four standard IDX files (gz or raw),
2. ``~/.deeplearning4j_tpu/mnist/``,
3. ONLY with explicit ``allow_synthetic=True`` (or env
   ``DL4J_TPU_ALLOW_SYNTHETIC=1``): a deterministic synthetic fallback
   (class-conditional blobs), flagged via ``.synthetic`` and a loud
   warning. Without the opt-in, missing data raises FileNotFoundError —
   a run "on MNIST" is never silently noise.

IDX parsing matches MnistManager: big-endian magic 2051/2049.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator

FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}
_ALIASES = {
    "test_images": ["t10k-images-idx3-ubyte", "t10k-idx3-ubyte"],
}


def _open_maybe_gz(path: str):
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    return open(path, "rb")


def read_idx_images(path: str) -> np.ndarray:
    """Parse an IDX3 image file (reference MnistManager.readImage);
    decoded by the native loader when built."""
    from deeplearning4j_tpu.native import parse_idx3

    with _open_maybe_gz(path) as f:
        buf = f.read()
    try:
        return parse_idx3(buf)
    except ValueError as e:
        raise ValueError(f"{e} in {path}") from None


def read_idx_labels(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"Bad IDX1 magic {magic} in {path}")
        return np.frombuffer(f.read(n), dtype=np.uint8)


def _find_file(directory: str, stem: str) -> Optional[str]:
    names = [FILES[stem]] + _ALIASES.get(stem, [])
    for n in names:
        p = os.path.join(directory, n)
        if os.path.exists(p) or os.path.exists(p + ".gz"):
            return p
    return None


def _synthetic_mnist(n: int, seed: int, train: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic class-conditional synthetic digits: each class c
    is a distinct fixed blob pattern + noise. Linearly separable but
    shaped/scaled exactly like MNIST (uint8 [n, 784], labels [n])."""
    rng = np.random.RandomState(seed + (0 if train else 1))
    proto_rng = np.random.RandomState(1234)
    protos = (proto_rng.rand(10, 784) > 0.82).astype(np.float32) * 200.0
    labels = rng.randint(0, 10, n).astype(np.uint8)
    imgs = protos[labels] + rng.randn(n, 784) * 25.0
    return np.clip(imgs, 0, 255).astype(np.uint8), labels


class MnistDataSetIterator(DataSetIterator):
    """Reference ``MnistDataSetIterator.java:30``: minibatches of
    normalized [0,1] 784-feature rows + one-hot labels."""

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, seed: int = 123,
                 data_dir: Optional[str] = None,
                 binarize: bool = False, shuffle: bool = True,
                 allow_synthetic: Optional[bool] = None):
        self.batch_size = batch_size
        self.synthetic = False
        directory = (
            data_dir
            or os.environ.get("DL4J_TPU_MNIST_DIR")
            or os.path.expanduser("~/.deeplearning4j_tpu/mnist")
        )
        img_stem = "train_images" if train else "test_images"
        lab_stem = "train_labels" if train else "test_labels"
        img_path = _find_file(directory, img_stem)
        lab_path = _find_file(directory, lab_stem)
        if img_path and lab_path:
            images = read_idx_images(img_path)
            labels = read_idx_labels(lab_path)
        else:
            from deeplearning4j_tpu.datasets.api import (
                resolve_synthetic_opt_in,
            )

            resolve_synthetic_opt_in(
                allow_synthetic, "MNIST",
                f"{directory!r} (or set DL4J_TPU_MNIST_DIR)",
            )
            n = num_examples or (60000 if train else 10000)
            images, labels = _synthetic_mnist(n, seed, train)
            self.synthetic = True
        if num_examples is not None:
            images = images[:num_examples]
            labels = labels[:num_examples]
        # keep uint8 + a permutation; batches are assembled on demand
        # by the native fused gather+normalize+one-hot kernel (1/4 the
        # resident memory of an eager float32 conversion)
        self._images = np.ascontiguousarray(images, np.uint8)
        self._labels_u8 = np.ascontiguousarray(labels, np.uint8)
        self._order = (
            np.random.RandomState(seed).permutation(len(images))
            if shuffle else np.arange(len(images))
        )
        self.binarize = binarize
        self._pos = 0

    def next(self) -> DataSet:
        from deeplearning4j_tpu.native import assemble_batch

        i = self._pos
        j = min(i + self.batch_size, len(self._images))
        self._pos = j
        feats, onehot = assemble_batch(
            self._images, self._labels_u8, self._order[i:j], 10
        )
        if self.binarize:
            feats = (feats > 0.5).astype(np.float32)
        return DataSet(features=feats, labels=onehot)

    def has_next(self) -> bool:
        return self._pos < len(self._images)

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self._images)

    def input_columns(self) -> int:
        return 784

    def total_outcomes(self) -> int:
        return 10
