"""DataSet containers and iterator SPI (reference: nd4j ``DataSet`` /
``MultiDataSet`` and ``datasets/iterator/DataSetIterator`` SPI,
SURVEY.md §2.1 datasets/iterator).

Host-side containers are numpy; conversion to device arrays happens
once, inside the jitted step's argument transfer (and under pjit the
transfer is sharded per device)."""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence

import numpy as np


@dataclass
class DataSet:
    """features/labels (+ optional masks) minibatch container."""

    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    # -- the ONE npz shard codec (export-based training + object-store
    # shards share this format; reference BatchAndExportDataSetsFunction)

    def save_npz(self, file) -> None:
        """Write this minibatch as an npz shard (``file``: path or
        file-like)."""
        arrays = {"features": np.asarray(self.features),
                  "labels": np.asarray(self.labels)}
        if self.features_mask is not None:
            arrays["features_mask"] = np.asarray(self.features_mask)
        if self.labels_mask is not None:
            arrays["labels_mask"] = np.asarray(self.labels_mask)
        np.savez(file, **arrays)

    def to_npz_bytes(self) -> bytes:
        import io

        buf = io.BytesIO()
        self.save_npz(buf)
        return buf.getvalue()

    @classmethod
    def load_npz(cls, file) -> "DataSet":
        """Read a shard written by ``save_npz`` (path or file-like)."""
        with np.load(file) as z:
            return cls(
                features=z["features"], labels=z["labels"],
                features_mask=(
                    z["features_mask"] if "features_mask" in z else None
                ),
                labels_mask=(
                    z["labels_mask"] if "labels_mask" in z else None
                ),
            )

    @classmethod
    def from_npz_bytes(cls, data: bytes) -> "DataSet":
        import io

        return cls.load_npz(io.BytesIO(data))

    def split_test_and_train(self, n_train: int):
        return (
            DataSet(
                self.features[:n_train], self.labels[:n_train],
                None if self.features_mask is None else self.features_mask[:n_train],
                None if self.labels_mask is None else self.labels_mask[:n_train],
            ),
            DataSet(
                self.features[n_train:], self.labels[n_train:],
                None if self.features_mask is None else self.features_mask[n_train:],
                None if self.labels_mask is None else self.labels_mask[n_train:],
            ),
        )

    def shuffle(self, seed: int = 0) -> "DataSet":
        rng = np.random.RandomState(seed)
        idx = rng.permutation(self.num_examples())
        return DataSet(
            self.features[idx], self.labels[idx],
            None if self.features_mask is None else self.features_mask[idx],
            None if self.labels_mask is None else self.labels_mask[idx],
        )

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        out = []
        n = self.num_examples()
        for i in range(0, n, batch_size):
            out.append(DataSet(
                self.features[i:i + batch_size],
                self.labels[i:i + batch_size],
                None if self.features_mask is None
                else self.features_mask[i:i + batch_size],
                None if self.labels_mask is None
                else self.labels_mask[i:i + batch_size],
            ))
        return out


@dataclass
class ChunkedDataSet:
    """k same-shaped minibatches pre-stacked on a leading axis
    ([k, b, ...]) — the payload an input pipeline hands the engines'
    fused multi-step dispatch DIRECTLY, skipping the per-batch
    split-and-restack round trip (each split/stack is a device
    dispatch; through a high-latency link those dominated streamed
    training). Produced by ``DevicePrefetchIterator(emit_chunks=True)``
    and consumed natively by the engines' scan path."""

    features: np.ndarray      # [k, b, ...]
    labels: np.ndarray        # [k, b, ...]
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    @property
    def k(self) -> int:
        return int(np.shape(self.features)[0])

    def num_examples(self) -> int:
        s = np.shape(self.features)
        return int(s[0]) * int(s[1])

    def to_datasets(self) -> List["DataSet"]:
        """Unstack into k per-batch DataSets (the fallback for
        consumers without a fused chunk path)."""
        def at(a, i):
            return None if a is None else a[i]

        return [
            DataSet(
                features=self.features[i], labels=self.labels[i],
                features_mask=at(self.features_mask, i),
                labels_mask=at(self.labels_mask, i),
            )
            for i in range(self.k)
        ]


@dataclass
class PlacedDataSet:
    """A minibatch that has already been materialized, cast, and
    placed on device (sharded when a mesh is in play) by an input
    pipeline — the payload ``datasets.prefetch.PrefetchIterator``
    hands the engines so the host->device scatter happens on the
    prefetch thread, off the step's critical path.

    ``features``/``labels``/masks are device arrays (or, for the DAG
    engine, lists of per-slot device arrays) in exactly the layout the
    consumer's placement function produced; consumers that receive one
    skip their own placement. ``num_rows`` is the count of VALID
    examples — when a trailing partial batch was padded up to the
    data-parallel degree, ``num_rows`` is the pre-padding size (the
    honest examples/sec signal) while the arrays carry the padded
    rows, masked out of the loss. ``has_masks`` caches whether any
    mask rides along (the trainer's step choice needs it without
    re-walking graph mask lists)."""

    features: object
    labels: object
    features_mask: object = None
    labels_mask: object = None
    num_rows: Optional[int] = None
    has_masks: Optional[bool] = None

    def num_examples(self) -> int:
        if self.num_rows is not None:
            return int(self.num_rows)
        first = self.features
        if isinstance(first, (list, tuple)):
            first = first[0]
        return int(np.shape(first)[0])


@dataclass
class PlacedChunk:
    """A block of k same-shaped minibatches stacked ``[k, b, ...]``
    AND already placed on device — the double-buffered feed payload of
    the megastep executor. A ``PrefetchIterator`` in chunk-stacking
    mode assembles the next block and runs its ``chunk_placement``
    (stack + ``device_put``, e.g. ``DistributedTrainer.place_chunk``)
    on the worker thread while the device executes the current
    megastep, so the K-step dispatch never waits on a host->device
    copy. ``num_rows`` counts valid examples across all k steps (the
    examples/sec signal)."""

    features: object          # [k, b, ...] device array (or list)
    labels: object
    features_mask: object = None
    labels_mask: object = None
    num_rows: Optional[int] = None

    @property
    def k(self) -> int:
        first = self.features
        if isinstance(first, (list, tuple)):
            first = first[0]
        return int(np.shape(first)[0])

    def num_examples(self) -> int:
        if self.num_rows is not None:
            return int(self.num_rows)
        first = self.features
        if isinstance(first, (list, tuple)):
            first = first[0]
        s = np.shape(first)
        return int(s[0]) * int(s[1])

    def to_datasets(self) -> List["DataSet"]:
        """Unstack into k per-batch DataSets (device slices) — the
        per-step fallback for trailing partial blocks."""
        def at(a, i):
            return None if a is None else a[i]

        return [
            DataSet(
                features=at(self.features, i), labels=at(self.labels, i),
                features_mask=at(self.features_mask, i),
                labels_mask=at(self.labels_mask, i),
            )
            for i in range(self.k)
        ]


@dataclass
class MultiDataSet:
    """Multi-input/multi-output container (reference nd4j MultiDataSet,
    consumed by ComputationGraph)."""

    features: Sequence[np.ndarray]
    labels: Sequence[np.ndarray]
    features_masks: Optional[Sequence[Optional[np.ndarray]]] = None
    labels_masks: Optional[Sequence[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])


class DataSetIterator:
    """Iterator SPI (reference ``DataSetIterator``). Subclasses
    implement ``__next__``/``has_next``/``reset``; iteration protocol
    provided for pythonic loops."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def next(self) -> DataSet:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def batch(self) -> int:
        raise NotImplementedError

    def total_examples(self) -> int:
        return -1


class ListDataSetIterator(DataSetIterator):
    """Iterate a pre-built list of minibatches (reference
    ``ListDataSetIterator``)."""

    def __init__(self, batches: Sequence[DataSet]):
        self._batches = list(batches)
        self._pos = 0

    def next(self) -> DataSet:
        ds = self._batches[self._pos]
        self._pos += 1
        return ds

    def has_next(self) -> bool:
        return self._pos < len(self._batches)

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        return self._batches[0].num_examples() if self._batches else 0

    def total_examples(self) -> int:
        return sum(b.num_examples() for b in self._batches)


class ExistingDataSetIterator(DataSetIterator):
    """Wrap any iterable of DataSets (reference
    ``ExistingDataSetIterator``)."""

    def __init__(self, iterable):
        self._iterable = iterable
        self._it = None

    def __iter__(self):
        self._it = iter(self._iterable)
        return self

    def __next__(self):
        if self._it is None:
            self._it = iter(self._iterable)
        return next(self._it)

    def reset(self):
        self._it = None


def resolve_synthetic_opt_in(
    allow_synthetic: Optional[bool], dataset: str, where: str,
) -> None:
    """Shared gate for synthetic-data fallbacks (MNIST/CIFAR): real
    data missing is an error unless the caller opted in explicitly or
    via ``DL4J_TPU_ALLOW_SYNTHETIC=1``; opting in still warns loudly.
    Returns None on opt-in; raises FileNotFoundError otherwise."""
    import os
    import warnings

    if allow_synthetic is None:
        allow_synthetic = os.environ.get(
            "DL4J_TPU_ALLOW_SYNTHETIC", ""
        ).lower() in ("1", "true", "on")
    if not allow_synthetic:
        raise FileNotFoundError(
            f"{dataset} data not found in {where}. Place the data "
            "there, or opt in to synthetic data with "
            "allow_synthetic=True / DL4J_TPU_ALLOW_SYNTHETIC=1."
        )
    warnings.warn(
        f"{dataset} data not found — using SYNTHETIC "
        f"class-conditional data (not real {dataset}).",
        RuntimeWarning, stacklevel=3,
    )
