"""Real raster data for egress-less environments.

The reference's MNIST tests download IDX files at first use
(``datasets/mnist/MnistManager.java``); this build environment has no
egress, so benching "on real data" needs a real dataset that ships
with the image. scikit-learn's ``load_digits`` bundle is exactly that:
1,797 real handwritten digit rasters (UCI Optical Recognition of
Handwritten Digits — genuine pen strokes, 8x8 @ 16 gray levels).

``ensure_digits_idx`` writes them ONCE as standard IDX files
(nearest-neighbor upscaled to 28x28 so LeNet-class configs run
unchanged), after which ``MnistDataSetIterator`` — and therefore the
native C++ IDX decoder (``native/loader.cpp``) — reads real bytes
end-to-end. The upscaling is declared in the marker file and in the
bench output: these are real handwritten images at coarser native
resolution than MNIST, not MNIST itself.
"""

from __future__ import annotations

import os
import struct
from typing import Optional

import numpy as np

_MARKER = "SOURCE.txt"
_TRAIN_N = 1500  # of 1797; remainder is the test split


def _upscale_nn(imgs: np.ndarray, size: int = 28) -> np.ndarray:
    """[n, 8, 8] -> [n, size, size] nearest neighbor."""
    idx = (np.arange(size) * imgs.shape[1]) // size
    return imgs[:, idx][:, :, idx]


def _write_idx3(path: str, images: np.ndarray) -> None:
    n, h, w = images.shape
    with open(path, "wb") as f:
        f.write(struct.pack(">iiii", 2051, n, h, w))
        f.write(np.ascontiguousarray(images, np.uint8).tobytes())


def _write_idx1(path: str, labels: np.ndarray) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack(">ii", 2049, len(labels)))
        f.write(np.ascontiguousarray(labels, np.uint8).tobytes())


def ensure_digits_idx(directory: Optional[str] = None) -> Optional[str]:
    """Materialize the real handwritten-digits dataset as IDX files
    (generate-once-and-cache). Returns the directory, or None when
    scikit-learn is unavailable."""
    directory = directory or os.path.expanduser(
        "~/.deeplearning4j_tpu/digits_idx"
    )
    marker = os.path.join(directory, _MARKER)
    if os.path.exists(marker):
        return directory
    try:
        from sklearn.datasets import load_digits
    except ImportError:
        return None
    d = load_digits()
    # 16 gray levels -> 0..255 uint8, like MNIST's byte range
    imgs = np.clip(d.images * 16.0, 0, 255).astype(np.uint8)
    imgs = _upscale_nn(imgs)
    labels = d.target.astype(np.uint8)
    rng = np.random.RandomState(42)
    perm = rng.permutation(len(imgs))
    imgs, labels = imgs[perm], labels[perm]
    os.makedirs(directory, exist_ok=True)
    _write_idx3(os.path.join(directory, "train-images-idx3-ubyte"),
                imgs[:_TRAIN_N])
    _write_idx1(os.path.join(directory, "train-labels-idx1-ubyte"),
                labels[:_TRAIN_N])
    _write_idx3(os.path.join(directory, "t10k-images-idx3-ubyte"),
                imgs[_TRAIN_N:])
    _write_idx1(os.path.join(directory, "t10k-labels-idx1-ubyte"),
                labels[_TRAIN_N:])
    with open(marker, "w") as f:
        f.write(
            "UCI Optical Recognition of Handwritten Digits via "
            "sklearn.datasets.load_digits: 1797 real handwritten "
            "rasters, 8x8@16-levels nearest-neighbor upscaled to "
            "28x28 uint8, shuffled seed=42, split 1500/297. Written "
            "as standard IDX so the native C++ decoder parses real "
            "bytes. NOT MNIST - declared wherever benched.\n"
        )
    return directory
