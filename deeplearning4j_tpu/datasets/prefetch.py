"""Prefetching training input pipeline.

The training loop was the last fully synchronous hot path in the
repo: the device idled while the host materialized the next
minibatch (CSV parse, augmentation, shard fetch), cast it, and — for
the distributed trainer — scattered it across the mesh with a
sharding-aware ``device_put``. The TensorFlow system paper makes
overlapping input preparation with accelerator compute a first-class
design requirement (PAPERS.md); this module is that overlap for the
training tier, the way ``serving/batcher.py`` is for serving.

:class:`PrefetchIterator` wraps any ``DataSetIterator`` with a
bounded background queue (depth ``queue_depth``, default 2). The
worker thread does the expensive parts off the critical path:

- **materialization** — ``base.next()`` runs on the worker, so a
  slow source (decode, network shard read) overlaps device compute;
- **placement** — an optional ``placement(ds)`` callable runs on the
  worker too. ``DistributedTrainer.place_minibatch`` is the intended
  placement: dtype cast + the ``NamedSharding(mesh, P("data"))``
  scatter that used to run inline in ``fit_minibatch``. The consumer
  then receives device-resident :class:`PlacedDataSet` batches and
  the step dispatch never waits on a host->device copy.

Contracts the tier-1 suite enforces:

- **deterministic ordering** — one worker, one FIFO queue: the
  consumer sees exactly the base iterator's batch order, so a
  pipelined ``fit`` replays the synchronous trajectory bitwise;
- **exception propagation** — a worker-thread failure (flaky source,
  placement error) surfaces on the consumer thread as
  ``DL4JFaultException`` (original exception chained as
  ``__cause__``), after every batch fetched before the fault has
  been delivered — no silent truncation, no lost batches;
- **clean shutdown** — ``shutdown()`` (also run by ``reset()`` and
  ``close()``) cancels and joins the worker even when it is blocked
  on a full queue.

Observability (PR-4 registry; catalogued in ARCHITECTURE.md):
``training_prefetch_queue_depth`` gauge (batches ready at each
consumer take) and ``training_prefetch_wait_ms`` histogram (how long
the consumer stalled for the next batch — the host-bound signal:
near-zero means the pipeline keeps the device fed, heavy upper
buckets mean the source is the bottleneck).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator
from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
from deeplearning4j_tpu.exceptions import DL4JFaultException
from deeplearning4j_tpu.observability import profiler

# fine buckets at the bottom (a fed pipeline waits ~0) and coarse at
# the top (a starved one waits a whole batch-materialization)
WAIT_MS_BUCKETS = (0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                   250.0, 1000.0)


class _PlacingIterator:
    """Producer-side adapter: run the user's placement on the worker
    thread so cast + sharded device_put overlap training (same shape
    as ``_EncodingIterator`` for the device-codec pipeline)."""

    def __init__(self, base: DataSetIterator,
                 placement: Optional[Callable]):
        self.base = base
        self.placement = placement

    def __iter__(self):
        for ds in self.base:
            yield self.placement(ds) if self.placement else ds

    def reset(self) -> None:
        if hasattr(self.base, "reset"):
            self.base.reset()


def _chunk_sig(ds):
    """Shape signature deciding which batches may stack into one
    megastep block (np.shape only — never materializes a device
    array)."""
    import numpy as np

    def sh(a):
        return None if a is None else tuple(np.shape(a))

    return (
        sh(getattr(ds, "features", None)),
        sh(getattr(ds, "labels", None)),
        sh(getattr(ds, "labels_mask", None)),
        sh(getattr(ds, "features_mask", None)),
    )


def _stack_host_chunk(batches):
    """Default chunk assembly: np.stack k host minibatches into one
    [k, b, ...] :class:`~.api.ChunkedDataSet` on the worker thread.
    The consumer-side driver does the (single) host->device transfer;
    a ``chunk_placement`` (e.g. ``DistributedTrainer.place_chunk``)
    replaces this with stack + sharded ``device_put`` so even that
    transfer leaves the critical path."""
    import numpy as np

    from deeplearning4j_tpu.datasets.api import ChunkedDataSet

    def stack(get):
        first = get(batches[0])
        if first is None:
            return None
        return np.stack([np.asarray(get(b)) for b in batches])

    return ChunkedDataSet(
        features=stack(lambda b: b.features),
        labels=stack(lambda b: b.labels),
        features_mask=stack(lambda b: getattr(b, "features_mask", None)),
        labels_mask=stack(lambda b: getattr(b, "labels_mask", None)),
    )


class _ChunkingIterator:
    """Producer-side adapter for megastep training: assemble blocks of
    ``k`` same-shaped minibatches ON THE WORKER THREAD and emit one
    chunk payload per block — the double-buffered feed. While the
    device executes the current K-step megastep, the worker is already
    stacking (and, via ``chunk_placement``, ``device_put``-ing) the
    NEXT block, so the fused dispatch never waits on assembly or the
    host->device copy.

    Multi-input batches (list-valued features) and shape-changing or
    trailing partial blocks pass through as individual (optionally
    ``placement``-placed) batches — the consumer's per-step fallback
    keeps the trajectory identical."""

    def __init__(self, base: DataSetIterator, k: int,
                 chunk_placement: Optional[Callable],
                 placement: Optional[Callable]):
        self.base = base
        self.k = int(k)
        self.chunk_placement = chunk_placement
        self.placement = placement

    def _assemble(self, buf):
        if self.chunk_placement is not None:
            return self.chunk_placement(buf)
        return _stack_host_chunk(buf)

    def _passthrough(self, ds):
        return self.placement(ds) if self.placement else ds

    def __iter__(self):
        buf, sig = [], None
        for ds in self.base:
            if isinstance(ds.features, (list, tuple)):
                for b in buf:
                    yield self._passthrough(b)
                buf, sig = [], None
                yield self._passthrough(ds)
                continue
            s = _chunk_sig(ds)
            if buf and s != sig:
                # a shape change ends the block early: a short block
                # still beats per-batch feed when >= 2 stacked
                if len(buf) >= 2:
                    yield self._assemble(buf)
                else:
                    yield self._passthrough(buf[0])
                buf = []
            sig = s
            buf.append(ds)
            if len(buf) >= self.k:
                yield self._assemble(buf)
                buf = []
        if len(buf) >= 2:
            yield self._assemble(buf)
        elif buf:
            yield self._passthrough(buf[0])

    def reset(self) -> None:
        if hasattr(self.base, "reset"):
            self.base.reset()


class PrefetchIterator(AsyncDataSetIterator):
    """Bounded background prefetch + optional device placement (see
    module docstring). Drop-in for any ``DataSetIterator``::

        it = PrefetchIterator(base, queue_depth=2,
                              placement=trainer.place_minibatch)
        trainer.fit(it, epochs=3)   # or: trainer.fit(base, prefetch=2)

    Without ``placement`` the worker only materializes host batches —
    still worthwhile when ``base.next()`` is expensive. With it, the
    consumer receives :class:`~..api.PlacedDataSet` device batches.

    ``validator`` (a :class:`~.validate.BatchValidator`) screens every
    base batch ON THE WORKER THREAD before placement — the validation
    host pass rides the same overlap as materialization, so a defended
    pipeline costs the consumer nothing extra; offenders go to
    ``quarantine`` (a :class:`~.validate.QuarantineStore`) and are
    skipped. The wrapped validating iterator is exposed as
    ``self.validating`` for ledger access.
    """

    def __init__(self, base: DataSetIterator, queue_depth: int = 2,
                 placement: Optional[Callable] = None,
                 registry=None, validator=None, quarantine=None,
                 megastep: int = 1,
                 chunk_placement: Optional[Callable] = None):
        if queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        self.validating = None
        if validator is not None:
            from deeplearning4j_tpu.datasets.validate import (
                ValidatingIterator,
            )

            if isinstance(base, ValidatingIterator):
                self.validating = base
            else:
                self.validating = base = ValidatingIterator(
                    base, validator, quarantine=quarantine,
                )
        self.megastep = int(megastep or 1)
        if self.megastep > 1:
            # chunk-stacking mode: each queue item is a whole K-batch
            # block, assembled (and placed) on the worker — the
            # double-buffered feed of the megastep executor
            producer = _ChunkingIterator(
                base, self.megastep, chunk_placement, placement
            )
        else:
            producer = _PlacingIterator(base, placement)
        super().__init__(producer, queue_depth)
        self._user_base = base
        if registry is None:
            from deeplearning4j_tpu.observability.metrics import (
                default_registry,
            )

            registry = default_registry()
        self.registry = registry
        self._depth_gauge = registry.gauge(
            "training_prefetch_queue_depth",
            help="prefetched batches ready at the last consumer take",
        )._default()
        self._wait_hist = registry.histogram(
            "training_prefetch_wait_ms", buckets=WAIT_MS_BUCKETS,
            help="consumer stall waiting for the next prefetched "
                 "batch (ms)",
        )._default()

    # -- instrumented queue take ---------------------------------------

    def _advance(self) -> None:
        t0 = time.perf_counter()
        super()._advance()
        wait_ms = (time.perf_counter() - t0) * 1000.0
        self._wait_hist.observe(wait_ms)
        prof = profiler.get_active_profiler()
        if prof is not None:
            # the step profiler folds this into the current step's
            # input_stall_ms decomposition slot
            prof.note_input_wait_ms(wait_ms)
        q = self._queue
        if q is not None:
            self._depth_gauge.set(q.qsize())

    # -- fault taxonomy -------------------------------------------------

    def next(self) -> DataSet:
        try:
            return super().next()
        except (StopIteration, DL4JFaultException):
            raise
        except BaseException as e:
            # a worker-thread fault (source iterator, placement) is a
            # runtime fault of the input pipeline: surface it in the
            # resilience taxonomy with the original chained
            raise DL4JFaultException(
                f"prefetch pipeline failed: {type(e).__name__}: {e}"
            ) from e

    def shutdown(self, timeout: float = 5.0,
                 raise_pending: bool = False) -> None:
        """Cancel and join the worker within ``timeout`` seconds.

        With ``raise_pending=True`` (the preemption path) a worker
        fault that was queued for delivery but never consumed — the
        consumer is shutting down early, so ``next()`` would never
        surface it — re-raises here as ``DL4JFaultException`` AFTER
        the join, so the fault is neither lost nor racing a live
        worker. The default (False) keeps ``close()``/``reset()``
        unwind-safe: raising from a ``finally`` would mask the
        original exception."""
        super().shutdown(timeout=timeout)
        if raise_pending:
            exc = self._pending_exc or self._exception
            self._pending_exc = None
            self._exception = None
            if exc is not None:
                raise DL4JFaultException(
                    f"prefetch worker fault pending at shutdown: "
                    f"{type(exc).__name__}: {exc}"
                ) from exc

    def close(self) -> None:
        """Alias for ``shutdown()`` (context-manager friendly)."""
        self.shutdown()

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- SPI delegation to the USER base (not the adapter) --------------

    def batch(self) -> int:
        return self._user_base.batch()

    def total_examples(self) -> int:
        return self._user_base.total_examples()
