"""Curves dataset (reference ``datasets/fetchers/CurvesDataFetcher
.java`` — downloads ``curves.ser``, a serialized DataSet of 28x28
synthetic curve images used for pretraining/autoencoder demos).

The reference's S3 artifact is a Java-serialized nd4j DataSet; here
the loader reads ``curves.npz`` (arrays ``features`` [n, 784] float,
optional ``labels``) from the data directory. When absent, the same
class of data is regenerated deterministically — parametric quadratic
Bezier strokes rasterized to 28x28, matching the original dataset's
construction idea — behind the standard synthetic opt-in gate.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator

WIDTH = HEIGHT = 28
N_EXAMPLES = 10000


def _raster_curve(rng: np.random.RandomState) -> np.ndarray:
    """One 28x28 grayscale quadratic-Bezier stroke."""
    p = rng.rand(3, 2) * (WIDTH - 5) + 2.0  # control points
    t = np.linspace(0.0, 1.0, 64)[:, None]
    pts = (
        (1 - t) ** 2 * p[0] + 2 * (1 - t) * t * p[1] + t ** 2 * p[2]
    )
    img = np.zeros((HEIGHT, WIDTH), np.float32)
    xi = np.clip(pts[:, 0].round().astype(int), 0, WIDTH - 1)
    yi = np.clip(pts[:, 1].round().astype(int), 0, HEIGHT - 1)
    img[yi, xi] = 1.0
    return img


def _synthetic_curves(n: int, seed: int) -> np.ndarray:
    rng = np.random.RandomState(seed)
    return np.stack(
        [_raster_curve(rng).ravel() for _ in range(n)]
    )


class CurvesDataSetIterator(DataSetIterator):
    """Unsupervised curve images, features == labels when none given
    (the reference feeds curves to pretraining; ``fetch`` returns the
    whole DataSet)."""

    def __init__(self, batch_size: int,
                 num_examples: Optional[int] = None,
                 data_dir: Optional[str] = None, seed: int = 123,
                 allow_synthetic: Optional[bool] = None):
        directory = (
            data_dir
            or os.environ.get("DL4J_TPU_CURVES_DIR")
            or os.path.expanduser("~/.deeplearning4j_tpu/curves")
        )
        path = os.path.join(directory, "curves.npz")
        self.synthetic = False
        if os.path.exists(path):
            with np.load(path) as z:
                feats = np.asarray(z["features"], np.float32)
                labels = (
                    np.asarray(z["labels"], np.float32)
                    if "labels" in z else feats
                )
        else:
            from deeplearning4j_tpu.datasets.api import (
                resolve_synthetic_opt_in,
            )

            resolve_synthetic_opt_in(
                allow_synthetic, "Curves",
                f"{path!r} (or set DL4J_TPU_CURVES_DIR)",
            )
            n = num_examples or N_EXAMPLES
            feats = _synthetic_curves(n, seed)
            labels = feats
            self.synthetic = True
        if num_examples is not None:
            feats, labels = feats[:num_examples], labels[:num_examples]
        self.batch_size = batch_size
        self._features = feats
        self._labels = labels
        self._pos = 0

    def next(self) -> DataSet:
        i = self._pos
        j = min(i + self.batch_size, len(self._features))
        self._pos = j
        return DataSet(features=self._features[i:j],
                       labels=self._labels[i:j])

    def has_next(self) -> bool:
        return self._pos < len(self._features)

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self._features)

    def input_columns(self) -> int:
        return self._features.shape[1]

    def total_outcomes(self) -> int:
        return self._labels.shape[1]
