"""Iris (reference: ``datasets/fetchers/IrisDataFetcher`` +
``IrisDataSetIterator``).

The reference bundles the classic 150-example dataset as a resource.
To keep this repo free of copied data files, the default is a
deterministic Iris-like generator (three 4-feature species clusters
with the classic means/spreads); drop the real ``iris.data`` CSV next
to ``DL4J_TPU_IRIS_FILE`` for exact parity.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator

# Classic per-species feature means / stds (sepal-l, sepal-w, petal-l,
# petal-w) — public summary statistics of Fisher's data.
_MEANS = np.array([
    [5.006, 3.428, 1.462, 0.246],   # setosa
    [5.936, 2.770, 4.260, 1.326],   # versicolor
    [6.588, 2.974, 5.552, 2.026],   # virginica
])
_STDS = np.array([
    [0.352, 0.379, 0.174, 0.105],
    [0.516, 0.314, 0.470, 0.198],
    [0.636, 0.322, 0.552, 0.275],
])


def load_iris(seed: int = 6) -> tuple:
    """Returns (features [150,4] float32, one-hot labels [150,3])."""
    path = os.environ.get("DL4J_TPU_IRIS_FILE")
    if path and os.path.exists(path):
        rows = []
        labels = []
        names: dict = {}
        with open(path) as f:
            for line in f:
                parts = line.strip().split(",")
                if len(parts) < 5:
                    continue
                rows.append([float(v) for v in parts[:4]])
                labels.append(names.setdefault(parts[4], len(names)))
        x = np.asarray(rows, np.float32)
        y = np.zeros((len(labels), 3), np.float32)
        y[np.arange(len(labels)), labels] = 1.0
        return x, y
    rng = np.random.RandomState(seed)
    xs, ys = [], []
    for c in range(3):
        xs.append(_MEANS[c] + rng.randn(50, 4) * _STDS[c])
        y = np.zeros((50, 3), np.float32)
        y[:, c] = 1.0
        ys.append(y)
    return (np.concatenate(xs).astype(np.float32), np.concatenate(ys))


class IrisDataSetIterator(DataSetIterator):
    """Reference ``IrisDataSetIterator(batch, numExamples)``."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150,
                 seed: int = 6, shuffle: bool = True):
        x, y = load_iris(seed)
        if shuffle:
            idx = np.random.RandomState(seed).permutation(len(x))
            x, y = x[idx], y[idx]
        self._features = x[:num_examples]
        self._labels = y[:num_examples]
        self.batch_size = batch_size
        self._pos = 0

    def next(self) -> DataSet:
        i = self._pos
        j = min(i + self.batch_size, len(self._features))
        self._pos = j
        return DataSet(features=self._features[i:j],
                       labels=self._labels[i:j])

    def has_next(self) -> bool:
        return self._pos < len(self._features)

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self._features)
