"""Validating / quarantining input pipeline (data-plane defense).

The resilience stack (PR 1/11/12) defends against process death and
numerical divergence, but every fit loop still *trusted* the batches
the data plane fed it — one mislabeled shard or silently-truncated
file in a streaming source poisons a run no NaN/Inf guard catches.
The reference framework ships exactly this boundary check
(``DataSetUtil``-style shape/label validation at the iterator SPI);
here it becomes a quarantining wrapper so long unattended runs keep
training instead of crashing:

- :class:`BatchSchema` — what a good minibatch looks like: trailing
  feature/label dims, expected dtypes kinds, label range, mask
  consistency. Inferred from a model conf (``from_model``) or given
  explicitly.
- :class:`BatchValidator` — vectorized host pass over one batch
  returning the violated reason codes (empty list = clean). One numpy
  scan per array; no device work.
- :class:`QuarantineStore` — bounded forensic store for rejected
  batches: atomic (temp + ``os.replace``) npz blobs plus a JSON
  manifest recording reason/stream offset/CRC, oldest-first eviction
  past ``max_bytes``, and ``replay()`` to re-materialize the rejects
  for offline inspection.
- :class:`ValidatingIterator` — ``DataSetIterator`` decorator that
  validates each base batch and, instead of raising, quarantines the
  offender and yields the next good batch. The stream offset of every
  reject is recorded (``skipped_offsets``) so a defended run's
  trajectory is exactly the clean run over the surviving batches —
  the bitwise contract the chaos suite asserts.

Wiring: ``PrefetchIterator(validator=...)`` runs the check on the
prefetch worker thread (the hot path pays nothing),
``DistributedTrainer.fit(validator=...)`` and the engines'
``fit(validator=...)`` wrap their iterator, and ``ContinualTrainer``
threads its ledger into the checkpoint manifest for kill/resume.

Metrics (PR-4 registry; catalogued in ARCHITECTURE.md):
``batches_quarantined_total{reason}`` and ``quarantine_bytes``.
"""

from __future__ import annotations

import json
import logging
import os
import zlib
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator

logger = logging.getLogger(__name__)

# reason codes, one per check (stable strings: they label the
# batches_quarantined_total counter and the quarantine manifest)
REASON_SHAPE = "shape"
REASON_DTYPE = "dtype"
REASON_NON_FINITE = "non_finite"
REASON_LABEL_RANGE = "label_range"
REASON_MASK_MISMATCH = "mask_mismatch"
REASON_MAGNITUDE = "magnitude"

ALL_REASONS = (
    REASON_SHAPE, REASON_DTYPE, REASON_NON_FINITE,
    REASON_LABEL_RANGE, REASON_MASK_MISMATCH, REASON_MAGNITUDE,
)

_QUARANTINE_METRICS = None


def _quarantine_metrics():
    global _QUARANTINE_METRICS
    if _QUARANTINE_METRICS is None:
        from deeplearning4j_tpu.observability.metrics import (
            default_registry,
        )

        reg = default_registry()
        _QUARANTINE_METRICS = (
            reg.counter(
                "batches_quarantined_total", labels=("reason",),
                help="input batches rejected by the validator, by "
                     "first violated reason code",
            ),
            reg.gauge(
                "quarantine_bytes",
                help="bytes currently held in the quarantine store",
            )._default(),
        )
    return _QUARANTINE_METRICS


@dataclass(frozen=True)
class BatchSchema:
    """What a clean minibatch looks like. All fields optional — a
    ``None`` disables that check:

    - ``feature_dim`` / ``label_dim``: expected TRAILING dim of
      features/labels (batch and, for sequences, time axes are free);
    - ``feature_dtype_kinds`` / ``label_dtype_kinds``: allowed numpy
      dtype kinds (default floats + ints — object/str payloads are
      the classic corrupt-CSV symptom);
    - ``label_range``: inclusive (lo, hi) every label must fall in
      (one-hot / probability targets: (0, 1));
    - ``max_abs``: magnitude ceiling for *finite* feature values —
      the finite-but-huge poison a NaN guard never sees.
    """

    feature_dim: Optional[int] = None
    label_dim: Optional[int] = None
    feature_dtype_kinds: Tuple[str, ...] = ("f", "i", "u")
    label_dtype_kinds: Tuple[str, ...] = ("f", "i", "u")
    label_range: Optional[Tuple[float, float]] = None
    max_abs: Optional[float] = None

    @classmethod
    def from_model(cls, model, *, max_abs: Optional[float] = 1e6
                   ) -> "BatchSchema":
        """Infer the schema from an engine's conf: first layer's
        ``n_in`` bounds the feature trailing dim, last layer's
        ``n_out`` the label trailing dim, and a softmax/sigmoid output
        activation implies labels in [0, 1]."""
        layers = list(getattr(model.conf, "layers", ()) or ())
        f_dim = None
        l_dim = None
        l_range = None
        if layers:
            n_in = int(getattr(layers[0], "n_in", 0) or 0)
            n_out = int(getattr(layers[-1], "n_out", 0) or 0)
            f_dim = n_in or None
            l_dim = n_out or None
            act = str(getattr(layers[-1], "activation", "") or "").lower()
            if act in ("softmax", "sigmoid"):
                l_range = (0.0, 1.0)
        return cls(feature_dim=f_dim, label_dim=l_dim,
                   label_range=l_range, max_abs=max_abs)


class BatchValidator:
    """Vectorized host-side batch checks against a
    :class:`BatchSchema`. ``validate(ds)`` returns the violated
    reason codes in a stable order (empty list = clean); `check`
    short-circuits cheap structural failures before touching values,
    so a wrong-dtype batch never trips numpy math on object arrays."""

    def __init__(self, schema: BatchSchema):
        self.schema = schema

    # -- individual checks (each returns a reason code or None) ---------

    def _check_dtype(self, ds) -> Optional[str]:
        s = self.schema
        for arr, kinds in ((ds.features, s.feature_dtype_kinds),
                           (ds.labels, s.label_dtype_kinds)):
            for a in _as_arrays(arr):
                if np.asarray(a).dtype.kind not in kinds:
                    return REASON_DTYPE
        return None

    def _check_shape(self, ds) -> Optional[str]:
        s = self.schema
        feats = _as_arrays(ds.features)
        labs = _as_arrays(ds.labels)
        for a in feats + labs:
            if np.asarray(a).ndim < 2:
                return REASON_SHAPE
        b = np.asarray(feats[0]).shape[0]
        for a in feats + labs:
            if np.asarray(a).shape[0] != b:
                return REASON_SHAPE
        if s.feature_dim is not None:
            for a in feats:
                sh = np.asarray(a).shape
                # dense [b, f] or sequence [b, f, t] layouts both carry
                # the feature dim at axis 1 in this stack
                if sh[1] != s.feature_dim:
                    return REASON_SHAPE
        if s.label_dim is not None:
            for a in labs:
                sh = np.asarray(a).shape
                if sh[1] != s.label_dim:
                    return REASON_SHAPE
        return None

    def _check_mask(self, ds) -> Optional[str]:
        feats = _as_arrays(ds.features)
        b = np.asarray(feats[0]).shape[0]
        for m in (_mask_list(ds, "features_mask")
                  + _mask_list(ds, "labels_mask")):
            ma = np.asarray(m)
            if ma.ndim < 1 or ma.shape[0] != b:
                return REASON_MASK_MISMATCH
            if ma.dtype.kind not in ("f", "i", "u", "b"):
                return REASON_MASK_MISMATCH
        return None

    def _check_values(self, ds) -> List[str]:
        s = self.schema
        reasons: List[str] = []
        finite = True
        magnitude_ok = True
        for a in _as_arrays(ds.features):
            arr = np.asarray(a)
            if arr.dtype.kind != "f":
                continue
            if not np.isfinite(arr).all():
                finite = False
            elif s.max_abs is not None and np.abs(arr).max(
                    initial=0.0) > s.max_abs:
                magnitude_ok = False
        label_ok = True
        for a in _as_arrays(ds.labels):
            arr = np.asarray(a)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                finite = False
                continue
            if s.label_range is not None:
                lo, hi = s.label_range
                if arr.size and (arr.min() < lo or arr.max() > hi):
                    label_ok = False
        if not finite:
            reasons.append(REASON_NON_FINITE)
        if not label_ok:
            reasons.append(REASON_LABEL_RANGE)
        if not magnitude_ok:
            reasons.append(REASON_MAGNITUDE)
        return reasons

    # -- the one public entry point -------------------------------------

    def validate(self, ds) -> List[str]:
        """All violated reason codes for one batch, structural checks
        first (a structural failure suppresses value checks — the
        arrays may not even support numpy math)."""
        r = self._check_dtype(ds)
        if r is not None:
            return [r]
        r = self._check_shape(ds)
        if r is not None:
            return [r]
        reasons = []
        r = self._check_mask(ds)
        if r is not None:
            reasons.append(r)
        reasons.extend(self._check_values(ds))
        return reasons


def _as_arrays(x) -> list:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return [a for a in x if a is not None]
    return [x]


def _mask_list(ds, name: str) -> list:
    plural = getattr(ds, name + "s", None)
    single = getattr(ds, name, None)
    return _as_arrays(plural if plural is not None else single)


class QuarantineStore:
    """Bounded forensic store for rejected batches.

    Layout: ``<dir>/manifest.json`` (atomic, one JSON doc) plus one
    ``q-<seq>.npz`` blob per quarantined batch. Every write is
    temp + ``os.replace`` (same discipline as the checkpoint store),
    the manifest lands AFTER its blob, and each entry records
    ``{file, reasons, offset, crc32, size}`` so ``replay()`` can
    CRC-verify before handing a batch back. ``max_bytes`` bounds the
    blob bytes with oldest-first eviction — quarantine is a window
    into recent poison, not an archive."""

    MANIFEST = "manifest.json"

    def __init__(self, directory, max_bytes: int = 64 * 2 ** 20,
                 registry=None):
        if max_bytes < 1:
            raise ValueError("max_bytes must be >= 1")
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_bytes = int(max_bytes)
        self._entries: List[dict] = []
        self._seq = 0
        self._load_manifest()

    # -- manifest -------------------------------------------------------

    def _load_manifest(self) -> None:
        path = self.directory / self.MANIFEST
        if not path.exists():
            return
        try:
            doc = json.loads(path.read_text())
            self._entries = list(doc.get("entries", []))
            self._seq = int(doc.get("seq", len(self._entries)))
        except (ValueError, OSError):
            logger.warning("unreadable quarantine manifest %s; "
                           "starting empty", path)

    def _write_manifest(self) -> None:
        from deeplearning4j_tpu.resilience.checkpoint import (
            atomic_write_bytes,
        )

        doc = {"format": 1, "seq": self._seq, "entries": self._entries}
        atomic_write_bytes(
            self.directory / self.MANIFEST,
            json.dumps(doc, indent=2).encode(),
        )
        _quarantine_metrics()[1].set(self.total_bytes())

    # -- write ----------------------------------------------------------

    def put(self, ds, reasons: Sequence[str], offset: int) -> dict:
        """Quarantine one rejected batch. Unserializable payloads
        (object arrays from a truly mangled source) are recorded
        manifest-only (``file: null``) — the ledger survives even when
        the bytes cannot."""
        from deeplearning4j_tpu.resilience.checkpoint import (
            atomic_write_bytes,
        )

        entry = {
            "file": None,
            "reasons": list(reasons),
            "offset": int(offset),
            "crc32": None,
            "size": 0,
        }
        try:
            data = ds.to_npz_bytes()
        except Exception:
            logger.warning(
                "quarantined batch at offset %d is unserializable; "
                "recording manifest-only", offset, exc_info=True,
            )
            data = None
        if data is not None:
            fname = f"q-{self._seq:08d}.npz"
            atomic_write_bytes(self.directory / fname, data)
            entry.update(file=fname, size=len(data),
                         crc32=zlib.crc32(data) & 0xFFFFFFFF)
        self._seq += 1
        self._entries.append(entry)
        self._evict()
        self._write_manifest()
        counter = _quarantine_metrics()[0]
        for reason in (reasons or ("unknown",)):
            counter.labels(reason).inc()
        from deeplearning4j_tpu.observability import flightrec
        flightrec.record_event(
            "quarantine", reasons=list(reasons or ("unknown",)),
            offset=int(offset), bytes=int(entry["size"]),
        )
        return entry

    def _evict(self) -> None:
        while (self.total_bytes() > self.max_bytes
               and any(e["file"] for e in self._entries)):
            victim = next(e for e in self._entries if e["file"])
            try:
                os.unlink(self.directory / victim["file"])
            except OSError:
                pass
            # keep the ledger line: the reject HAPPENED even after
            # its bytes age out
            victim.update(file=None, size=0, crc32=None)
            victim["evicted"] = True

    # -- read -----------------------------------------------------------

    def entries(self) -> List[dict]:
        return [dict(e) for e in self._entries]

    def total_bytes(self) -> int:
        return sum(int(e.get("size", 0)) for e in self._entries)

    def replay(self):
        """Yield ``(entry, DataSet)`` for every quarantined batch whose
        blob survives and CRC-verifies (forensics: re-run the
        validator, eyeball the arrays). Corrupt/evicted blobs yield
        ``(entry, None)``."""
        for entry in self._entries:
            ds = None
            if entry.get("file"):
                path = self.directory / entry["file"]
                try:
                    data = path.read_bytes()
                    if (zlib.crc32(data) & 0xFFFFFFFF) == int(
                            entry.get("crc32") or -1):
                        ds = DataSet.from_npz_bytes(data)
                    else:
                        logger.warning(
                            "quarantine blob %s failed CRC", path)
                except OSError:
                    logger.warning("quarantine blob %s unreadable",
                                   path)
            yield dict(entry), ds


class ValidatingIterator(DataSetIterator):
    """``DataSetIterator`` decorator: validate every base batch,
    quarantine the rejects, yield only clean batches.

    A one-item lookahead keeps ``has_next()`` honest when the TAIL of
    the stream is poison (the base may have batches left, all of which
    get rejected). ``offset`` counts batches consumed FROM THE BASE
    (quarantined ones included) — the manifest key that makes a
    resumed stream line up; ``skipped_offsets`` are the rejected ones.
    ``fast_forward(n)`` re-consumes ``n`` base batches without
    validating or yielding (resume: the checkpoint ledger says the
    first ``n`` were already handled)."""

    def __init__(self, base: DataSetIterator, validator: BatchValidator,
                 quarantine: Optional[QuarantineStore] = None,
                 max_quarantined: Optional[int] = None):
        self.base = base
        self.validator = validator
        self.quarantine = quarantine
        self.max_quarantined = max_quarantined
        self.offset = 0                    # base batches consumed
        self.skipped_offsets: List[int] = []
        self.reason_counts: dict = {}
        self._lookahead: Optional[DataSet] = None
        self._plain_iter = None            # lazy iter() over list bases

    # -- resume ---------------------------------------------------------

    def fast_forward(self, n: int) -> None:
        """Skip ``n`` base batches (already consumed before a crash,
        per the checkpoint ledger) without validating them."""
        for _ in range(int(n)):
            if not self._base_has_next():
                break
            self._base_next()
            self.offset += 1

    # -- the filtering core ---------------------------------------------

    def _base_has_next(self) -> bool:
        if hasattr(self.base, "has_next"):
            return self.base.has_next()
        return True  # plain-iterable base: rely on StopIteration

    def _base_next(self) -> DataSet:
        if hasattr(self.base, "next"):
            return self.base.next()
        # plain list/iterable base (the engines' fit accepts those):
        # hold one iter() handle so repeated pulls advance it
        if self._plain_iter is None:
            self._plain_iter = iter(self.base)
        return next(self._plain_iter)

    def _pull_clean(self) -> Optional[DataSet]:
        while self._base_has_next():
            try:
                ds = self._base_next()
            except StopIteration:
                return None
            at = self.offset
            self.offset += 1
            reasons = self.validator.validate(ds)
            if not reasons:
                return ds
            self.skipped_offsets.append(at)
            for reason in reasons:
                self.reason_counts[reason] = (
                    self.reason_counts.get(reason, 0) + 1
                )
            logger.warning(
                "quarantining batch at stream offset %d: %s",
                at, ",".join(reasons),
            )
            if self.quarantine is not None:
                self.quarantine.put(ds, reasons, at)
            else:
                _quarantine_metrics()[0].labels(reasons[0]).inc()
            if (self.max_quarantined is not None
                    and len(self.skipped_offsets)
                    > self.max_quarantined):
                from deeplearning4j_tpu.exceptions import (
                    DL4JFaultException,
                )

                raise DL4JFaultException(
                    f"{len(self.skipped_offsets)} batches "
                    "quarantined (> max_quarantined="
                    f"{self.max_quarantined}) — the source looks "
                    "systematically poisoned, refusing to train on "
                    "the remainder"
                )
        return None

    # -- DataSetIterator SPI --------------------------------------------

    def has_next(self) -> bool:
        if self._lookahead is None:
            self._lookahead = self._pull_clean()
        return self._lookahead is not None

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        ds = self._lookahead
        self._lookahead = None
        return ds

    def reset(self) -> None:
        if hasattr(self.base, "reset"):
            self.base.reset()
        self.offset = 0
        self._lookahead = None
        self._plain_iter = None

    def batch(self) -> int:
        return self.base.batch() if hasattr(self.base, "batch") else 0

    def total_examples(self) -> int:
        if hasattr(self.base, "total_examples"):
            return self.base.total_examples()
        return 0

    # -- ledger ---------------------------------------------------------

    def ledger(self) -> dict:
        """The manifest-ready quarantine ledger: how far into the base
        stream we are and which offsets were rejected (what
        ``ContinualTrainer`` persists for bitwise kill/resume)."""
        return {
            "offset": int(self.offset),
            "skipped": [int(i) for i in self.skipped_offsets],
            "reasons": dict(self.reason_counts),
        }
