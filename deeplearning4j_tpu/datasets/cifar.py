"""CIFAR-10 (reference: ``datasets/iterator/impl/CifarDataSetIterator
.java`` over datavec's ``CifarLoader`` binary parsing).

Parses the standard binary distribution (``cifar-10-batches-bin``:
``data_batch_{1..5}.bin`` / ``test_batch.bin``, records of 1 label byte
+ 3072 RGB bytes) and the python pickle distribution
(``cifar-10-batches-py``). No egress in this environment, so resolution
order mirrors :mod:`deeplearning4j_tpu.datasets.mnist`:

1. ``data_dir`` argument or ``DL4J_TPU_CIFAR_DIR`` env var,
2. ``~/.deeplearning4j_tpu/cifar10/``,
3. ONLY with explicit ``allow_synthetic=True`` (or env
   ``DL4J_TPU_ALLOW_SYNTHETIC=1``): deterministic synthetic
   class-conditional images, flagged via ``.synthetic`` + warning.

Features are NCHW float32 in [0, 1] (``InputType.convolutional(32, 32,
3)``); ``flat=True`` yields ``[n, 3072]`` rows for
``InputType.convolutional_flat``.
"""

from __future__ import annotations

import os
import pickle
from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator

HEIGHT, WIDTH, CHANNELS, NUM_LABELS = 32, 32, 3, 10
NUM_TRAIN_IMAGES, NUM_TEST_IMAGES = 50000, 10000
_REC = 1 + CHANNELS * HEIGHT * WIDTH  # 3073-byte binary record

LABELS = [
    "airplane", "automobile", "bird", "cat", "deer",
    "dog", "frog", "horse", "ship", "truck",
]


def _read_bin(path: str) -> Tuple[np.ndarray, np.ndarray]:
    from deeplearning4j_tpu.native import split_cifar

    with open(path, "rb") as f:
        buf = f.read()
    if len(buf) % _REC:
        raise ValueError(f"{path}: size {len(buf)} not a multiple of {_REC}")
    images, labels = split_cifar(buf)  # native C++ when available
    return images.reshape(-1, CHANNELS, HEIGHT, WIDTH), labels


def _read_py(path: str) -> Tuple[np.ndarray, np.ndarray]:
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    images = np.asarray(d[b"data"], np.uint8).reshape(
        -1, CHANNELS, HEIGHT, WIDTH
    )
    labels = np.asarray(d[b"labels"], np.uint8)
    return images, labels


def _candidate_dirs(data_dir: Optional[str]) -> List[str]:
    base = (
        data_dir
        or os.environ.get("DL4J_TPU_CIFAR_DIR")
        or os.path.expanduser("~/.deeplearning4j_tpu/cifar10")
    )
    return [
        base,
        os.path.join(base, "cifar-10-batches-bin"),
        os.path.join(base, "cifar-10-batches-py"),
    ]


def _load_real(data_dir: Optional[str], train: bool):
    bin_names = (
        [f"data_batch_{i}.bin" for i in range(1, 6)] if train
        else ["test_batch.bin"]
    )
    py_names = (
        [f"data_batch_{i}" for i in range(1, 6)] if train
        else ["test_batch"]
    )
    for d in _candidate_dirs(data_dir):
        if all(os.path.exists(os.path.join(d, n)) for n in bin_names):
            parts = [_read_bin(os.path.join(d, n)) for n in bin_names]
        elif all(os.path.exists(os.path.join(d, n)) for n in py_names):
            parts = [_read_py(os.path.join(d, n)) for n in py_names]
        else:
            continue
        images = np.concatenate([p[0] for p in parts])
        labels = np.concatenate([p[1] for p in parts])
        return images, labels
    return None


def _synthetic_cifar(n: int, seed: int, train: bool):
    """Class-conditional color-blob images, shaped/scaled like CIFAR."""
    rng = np.random.RandomState(seed + (0 if train else 1))
    proto_rng = np.random.RandomState(4321)
    protos = proto_rng.rand(
        NUM_LABELS, CHANNELS, HEIGHT, WIDTH
    ).astype(np.float32) * 180.0
    labels = rng.randint(0, NUM_LABELS, n).astype(np.uint8)
    imgs = protos[labels] + rng.randn(n, CHANNELS, HEIGHT, WIDTH) * 30.0
    return np.clip(imgs, 0, 255).astype(np.uint8), labels


class CifarDataSetIterator(DataSetIterator):
    """Minibatches of CIFAR-10 (reference
    ``CifarDataSetIterator.java:1``)."""

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 train: bool = True, data_dir: Optional[str] = None,
                 seed: int = 123, shuffle: bool = True, flat: bool = False,
                 allow_synthetic: Optional[bool] = None):
        self.batch_size = batch_size
        self.synthetic = False
        loaded = _load_real(data_dir, train)
        if loaded is not None:
            images, labels = loaded
        else:
            from deeplearning4j_tpu.datasets.api import (
                resolve_synthetic_opt_in,
            )

            resolve_synthetic_opt_in(
                allow_synthetic, "CIFAR-10",
                f"{_candidate_dirs(data_dir)!r} (or set "
                "DL4J_TPU_CIFAR_DIR)",
            )
            n = num_examples or (
                NUM_TRAIN_IMAGES if train else NUM_TEST_IMAGES
            )
            images, labels = _synthetic_cifar(n, seed, train)
            self.synthetic = True
        if num_examples is not None:
            images, labels = images[:num_examples], labels[:num_examples]
        # uint8 rows + permutation; batches assembled on demand by the
        # native fused gather+normalize+one-hot kernel
        self._images = np.ascontiguousarray(
            images.reshape(len(images), -1), np.uint8
        )
        self._labels_u8 = np.ascontiguousarray(labels, np.uint8)
        self._order = (
            np.random.RandomState(seed).permutation(len(images))
            if shuffle else np.arange(len(images))
        )
        self.flat = flat
        self._pos = 0

    def next(self) -> DataSet:
        from deeplearning4j_tpu.native import assemble_batch

        i = self._pos
        j = min(i + self.batch_size, len(self._images))
        self._pos = j
        feats, onehot = assemble_batch(
            self._images, self._labels_u8, self._order[i:j],
            NUM_LABELS,
        )
        if not self.flat:
            feats = feats.reshape(len(feats), CHANNELS, HEIGHT, WIDTH)
        return DataSet(features=feats, labels=onehot)

    def has_next(self) -> bool:
        return self._pos < len(self._images)

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self._images)

    def input_columns(self) -> int:
        return CHANNELS * HEIGHT * WIDTH

    def total_outcomes(self) -> int:
        return NUM_LABELS
