"""Iterator wrappers (reference: ``datasets/iterator/*`` — notably
``AsyncDataSetIterator.java:36`` with its background prefetch thread +
blocking queue, ``MultipleEpochsIterator``, ``SamplingDataSetIterator``).

On TPU the async prefetch overlaps host-side data preparation with
device compute exactly like the reference overlaps ETL with training;
device transfer itself happens inside the jitted step.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator

_SENTINEL = object()


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch (reference
    ``AsyncDataSetIterator.java:36,:75-76,:256`` — IteratorRunnable
    feeding a LinkedBlockingQueue of ``queue_size``)."""

    def __init__(self, base: DataSetIterator, queue_size: int = 2):
        if queue_size < 1:
            raise ValueError("queue_size must be >= 1")
        self.base = base
        self.queue_size = queue_size
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self._exception: Optional[BaseException] = None
        self._pending_exc: Optional[BaseException] = None
        self._next_item = None
        self._needs_advance = False
        self._started = False

    # -- internals -----------------------------------------------------

    def _runner(self, q: "queue.Queue", stop: threading.Event) -> None:
        def put(item) -> bool:
            # bounded put that gives up when the consumer cancels
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            for ds in self.base:
                if not put(ds):
                    return  # cancelled; no sentinel needed
        except BaseException as e:  # surfaced on the consumer thread
            self._exception = e
        finally:
            put(_SENTINEL)

    def _start(self) -> None:
        self._queue = queue.Queue(maxsize=self.queue_size)
        self._stop = threading.Event()
        self._exception = None
        self._thread = threading.Thread(
            target=self._runner, args=(self._queue, self._stop), daemon=True
        )
        self._thread.start()
        self._started = True
        self._needs_advance = False
        self._advance()

    def _advance(self) -> None:
        item = self._queue.get()
        if item is _SENTINEL:
            self._next_item = None
            if self._exception is not None:
                # deliver already-fetched batches first; raise on the
                # call that would need the failed batch
                self._pending_exc = self._exception
                self._exception = None
        else:
            self._next_item = item

    # -- DataSetIterator -----------------------------------------------

    def has_next(self) -> bool:
        if not self._started:
            self._start()
        elif self._needs_advance:
            # deferred take (see next()): block for the following item
            # only now, AFTER the consumer has processed the previous
            # one — an eager advance inside next() would stall the
            # consumer on item N+1's production before it could even
            # start working on item N, fully serializing a producer
            # that is slower than the consumer
            self._needs_advance = False
            self._advance()
        return self._next_item is not None or self._pending_exc is not None

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        if self._next_item is None and self._pending_exc is not None:
            exc, self._pending_exc = self._pending_exc, None
            raise exc
        ds = self._next_item
        self._next_item = None
        self._needs_advance = True
        return ds

    def reset(self) -> None:
        self.shutdown()
        if hasattr(self.base, "reset"):
            self.base.reset()
        self._started = False
        self._next_item = None
        self._needs_advance = False

    def shutdown(self, timeout: float = 5.0) -> None:
        """Cancel and join the worker (reference ``shutdown()``). Safe
        to call mid-stream: the producer observes the stop flag instead
        of blocking on a full queue. The join is bounded by
        ``timeout`` seconds — a worker that refuses to die raises
        instead of hanging the caller (the preemption path runs this
        inside a grace window)."""
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            # unblock a producer stuck between puts
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():  # pragma: no cover
                raise RuntimeError("AsyncDataSetIterator worker leaked")
        self._thread = None

    def batch(self) -> int:
        return self.base.batch()

    def total_examples(self) -> int:
        return self.base.total_examples()


class _EncodingIterator:
    """Producer-side adapter for DevicePrefetchIterator: encode each
    host batch and START its host->device copy on the worker thread,
    so transfer overlaps both decode and training. ``batch_group``
    batches share ONE ``device_put`` (leaves stacked on a new leading
    axis): per-transfer latency — not just bandwidth — is the scarce
    resource on some interconnects, so grouping amortizes it the way
    the engines' scan chunks amortize dispatches."""

    def __init__(self, base, host_encode, batch_group: int = 1):
        self.base = base
        self.host_encode = host_encode
        self.batch_group = max(1, int(batch_group))

    def _encode(self, ds):
        if self.host_encode is not None:
            return self.host_encode(ds)
        return (
            np.asarray(ds.features), np.asarray(ds.labels),
            getattr(ds, "labels_mask", None),
            getattr(ds, "features_mask", None),
        )

    @staticmethod
    def _shapes(tree):
        import jax

        return tuple(
            (np.shape(l), np.asarray(l).dtype.str)
            for l in jax.tree_util.tree_leaves(tree)
        )

    def __iter__(self):
        import jax

        if self.batch_group == 1:
            # ungrouped fast path: one put per batch, no stack copy
            for ds in self.base:
                yield ("single", 1, jax.tree_util.tree_map(
                    jax.device_put, self._encode(ds)
                ))
            return

        def put_group(group):
            # one device_put for the whole group; async — the copy
            # proceeds while the worker encodes the next group
            stacked = jax.tree_util.tree_map(
                lambda *ls: np.stack(ls), *group
            )
            return ("group", len(group),
                    jax.tree_util.tree_map(jax.device_put, stacked))

        group, sig = [], None
        for ds in self.base:
            payload = self._encode(ds)
            s = self._shapes(payload)
            if group and (s != sig or len(group) >= self.batch_group):
                yield put_group(group)
                group = []
            sig = s
            group.append(payload)
        if group:
            yield put_group(group)

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()


class DevicePrefetchIterator(AsyncDataSetIterator):
    """Device-affinity prefetch: the AsyncDataSetIterator thread PLUS
    placement — the worker encodes each host batch (optional
    ``host_encode``, e.g. 1-bit packing of binarized images), starts
    its asynchronous host->device copy, and the consumer receives
    device-RESIDENT DataSets (through the optional jitted
    ``device_decode``). The engines' chunk stacking then runs on
    device, so a cold ``fit()`` streams: decode (host, C++ loader) ->
    encoded transfer -> on-device decode -> train, all overlapped.

    Reference analog: ``AsyncDataSetIterator.java:36`` pins its
    prefetch thread to a device for affinity. The TPU-native version
    optimizes what the reference could not: the scarce resource is the
    host->device link, so what crosses it is the *encoded* payload
    (e.g. 98 bytes/example for bit-packed binarized MNIST instead of
    3,136 bytes of float32) and bit-unpack/normalize/one-hot run on
    device, where they are free against the MXU.

    - ``host_encode(ds) -> pytree of np arrays`` (worker thread)
    - ``device_decode(tree) -> (features, labels, labels_mask,
      features_mask)`` — vmapped over the transfer group and jitted on
      first use, one compile per payload shape.
    - ``batch_group``: batches per ``device_put`` (grouped transfer —
      amortizes per-transfer latency; decoded as one dispatch, then
      split on device).
    - ``emit_chunks``: yield each transfer group as ONE
      :class:`ChunkedDataSet` ([k, b, ...]) instead of splitting it —
      the engines' fused scan consumes it directly, so a streamed
      group costs ~2 dispatches instead of ~2k+2 (split + restack).
    """

    def __init__(self, base, queue_size: int = 2, host_encode=None,
                 device_decode=None, batch_group: int = 1,
                 emit_chunks: bool = False):
        super().__init__(
            _EncodingIterator(base, host_encode, batch_group),
            queue_size,
        )
        self._device_decode = device_decode
        self._jit_decode = None
        self._jit_fallback: dict = {}
        self._user_base = base
        self._pending: list = []
        self._emit_chunks = emit_chunks

    def has_next(self) -> bool:
        return bool(self._pending) or super().has_next()

    def _decode_fn(self, grouped: bool):
        """Jitted decode, cached ON the codec function so it (and its
        compiled programs) survive iterator recreation — a fresh
        fit() per epoch/window must not retrace. Codecs that cannot
        carry attributes (bound methods, partials) fall back to a
        per-ITERATOR cache, never a per-call jit."""
        import jax

        attr = "_dl4j_jit_group" if grouped else "_dl4j_jit_single"
        fn = getattr(self._device_decode, attr, None)
        if fn is None:
            fn = self._jit_fallback.get(attr)
        if fn is None:
            fn = jax.jit(
                jax.vmap(self._device_decode) if grouped
                else self._device_decode
            )
            try:
                setattr(self._device_decode, attr, fn)
            except AttributeError:
                self._jit_fallback[attr] = fn
        return fn

    def next(self) -> DataSet:
        if self._pending:
            return self._pending.pop(0)
        tag, k, stacked = super().next()
        if tag == "single":
            if self._device_decode is not None:
                f, l, lm, fm = self._decode_fn(False)(stacked)
            else:
                f, l, lm, fm = stacked
            return DataSet(features=f, labels=l, labels_mask=lm,
                           features_mask=fm)
        if self._device_decode is not None:
            if self._jit_decode is None:
                self._jit_decode = self._decode_fn(True)
            f, l, lm, fm = self._jit_decode(stacked)
        else:
            f, l, lm, fm = stacked
        from deeplearning4j_tpu.datasets.api import ChunkedDataSet

        chunk = ChunkedDataSet(
            features=f, labels=l, labels_mask=lm, features_mask=fm,
        )
        if self._emit_chunks:
            return chunk
        self._pending = chunk.to_datasets()
        return self._pending.pop(0)

    def reset(self) -> None:
        self._pending = []
        super().reset()

    def batch(self) -> int:
        return self._user_base.batch()

    def total_examples(self) -> int:
        return self._user_base.total_examples()


def make_packbits_codec(n_features: int, n_classes: int,
                        threshold: float = 0.5):
    """(host_encode, device_decode) for binary-valued feature rows +
    one-hot labels: features pack to 1 bit/pixel on host (32x fewer
    bytes over the link than float32), labels ride as class indices;
    unpack and one-hot run on device. Exact for any features that are
    strictly {0,1}-valued after thresholding (e.g. binarized MNIST).
    """

    # class indices ride at the narrowest width that can hold them
    if n_classes <= 256:
        idx_dtype = np.uint8
    elif n_classes <= 65536:
        idx_dtype = np.uint16
    else:
        idx_dtype = np.int32

    def host_encode(ds):
        f = np.asarray(ds.features)
        bits = (
            (f > threshold) if f.dtype.kind == "f" else (f != 0)
        ).astype(np.uint8)
        packed = np.packbits(bits, axis=1)  # big-endian bit order
        y = np.asarray(ds.labels)
        if y.ndim == 2:  # one-hot -> index
            y = np.argmax(y, axis=1)
        return packed, y.astype(idx_dtype)

    def device_decode(tree):
        import jax
        import jax.numpy as jnp

        packed, y = tree
        shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
        bits = (packed[:, :, None] >> shifts) & jnp.uint8(1)
        x = bits.reshape(packed.shape[0], -1)[:, :n_features]
        onehot = jax.nn.one_hot(y, n_classes, dtype=jnp.uint8)
        return x, onehot, None, None

    return host_encode, device_decode


class MultipleEpochsIterator(DataSetIterator):
    """Present N epochs of a base iterator as one pass (reference
    ``MultipleEpochsIterator``)."""

    def __init__(self, epochs: int, base: DataSetIterator):
        self.epochs = epochs
        self.base = base
        self._epoch = 0

    def has_next(self) -> bool:
        if self.base.has_next():
            return True
        if self._epoch + 1 < self.epochs:
            self._epoch += 1
            self.base.reset()
            return self.base.has_next()
        return False

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.base.next()

    def reset(self) -> None:
        self._epoch = 0
        self.base.reset()

    def batch(self) -> int:
        return self.base.batch()


class SamplingDataSetIterator(DataSetIterator):
    """Sample minibatches with replacement from a full DataSet
    (reference ``SamplingDataSetIterator``)."""

    def __init__(self, full: DataSet, batch_size: int,
                 total_batches: int, seed: int = 123):
        self.full = full
        self.batch_size = batch_size
        self.total_batches = total_batches
        self._rng = np.random.RandomState(seed)
        self._seed = seed
        self._count = 0

    def has_next(self) -> bool:
        return self._count < self.total_batches

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        self._count += 1
        idx = self._rng.randint(0, self.full.num_examples(),
                                self.batch_size)
        return DataSet(
            features=self.full.features[idx],
            labels=self.full.labels[idx],
            features_mask=(None if self.full.features_mask is None
                           else self.full.features_mask[idx]),
            labels_mask=(None if self.full.labels_mask is None
                         else self.full.labels_mask[idx]),
        )

    def reset(self) -> None:
        self._count = 0
        self._rng = np.random.RandomState(self._seed)

    def batch(self) -> int:
        return self.batch_size


class ReconstructionDataSetIterator(DataSetIterator):
    """Wraps an iterator so labels == features (reference
    ``ReconstructionDataSetIterator`` — autoencoder training over a
    labeled dataset)."""

    def __init__(self, base: DataSetIterator):
        self.base = base

    def has_next(self) -> bool:
        return self.base.has_next()

    def next(self) -> DataSet:
        ds = self.base.next()
        return DataSet(features=ds.features, labels=ds.features,
                       features_mask=ds.features_mask,
                       labels_mask=ds.features_mask)

    def reset(self) -> None:
        self.base.reset()

    def batch(self) -> int:
        return self.base.batch()


class MovingWindowDataSetIterator(DataSetIterator):
    """Sliding windows over the time axis of one sequence DataSet
    (reference ``MovingWindowBaseDataSetIterator`` — windowed slices
    become independent examples)."""

    def __init__(self, full: DataSet, batch_size: int, window: int,
                 stride: int = 1):
        if full.features_mask is not None or full.labels_mask is not None:
            raise ValueError(
                "MovingWindow does not window mask arrays — padded "
                "timesteps would become real training data; slice "
                "masked sequences to their valid lengths first"
            )
        feats = np.asarray(full.features)
        labels = np.asarray(full.labels)
        if feats.ndim != 3:
            raise ValueError(
                "MovingWindow needs [batch, features, time] sequences"
            )
        t = feats.shape[2]
        if labels.ndim == 3 and labels.shape[2] != t:
            raise ValueError(
                f"labels time length {labels.shape[2]} != features "
                f"time length {t}"
            )
        if window > t:
            raise ValueError(f"window {window} > sequence length {t}")
        xs, ys = [], []
        for start in range(0, t - window + 1, stride):
            xs.append(feats[:, :, start:start + window])
            ys.append(
                labels[:, :, start:start + window]
                if labels.ndim == 3 else labels
            )
        self._features = np.concatenate(xs)
        self._labels = np.concatenate(ys)
        self.batch_size = batch_size
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._features)

    def next(self) -> DataSet:
        i = self._pos
        j = min(i + self.batch_size, len(self._features))
        self._pos = j
        return DataSet(features=self._features[i:j],
                       labels=self._labels[i:j])

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self._features)


class INDArrayDataSetIterator(DataSetIterator):
    """Batches from raw (features, labels) array pairs (reference
    ``INDArrayDataSetIterator``)."""

    def __init__(self, pairs, batch_size: int):
        feats, labels = [], []
        for i, (f, l) in enumerate(pairs):
            f = np.asarray(f)
            l = np.asarray(l)
            f = f if f.ndim > 1 else f[None, :]
            l = l if l.ndim > 1 else l[None, :]
            if len(f) != len(l):
                # per-pair check: totals can cancel out and misalign
                # every later example's labels
                raise ValueError(
                    f"pair {i}: features have {len(f)} examples but "
                    f"labels have {len(l)}"
                )
            feats.append(f)
            labels.append(l)
        if not feats:
            raise ValueError("no (features, labels) pairs given")
        self._features = np.concatenate(feats)
        self._labels = np.concatenate(labels)
        self.batch_size = batch_size
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._features)

    def next(self) -> DataSet:
        i = self._pos
        j = min(i + self.batch_size, len(self._features))
        self._pos = j
        return DataSet(features=self._features[i:j],
                       labels=self._labels[i:j])

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self._features)


class RetryingDataSetIterator(DataSetIterator):
    """Retry decorator for flaky-source iterators (cloud shard reads,
    NFS hiccups): ``next()`` runs under a ``resilience.RetryPolicy``
    with exponential backoff, raising ``RetryExhaustedException`` past
    the budget. Wrap the SOURCE iterator (e.g. CloudDataSetIterator),
    then stack ``AsyncDataSetIterator`` on top so retries happen on
    the prefetch thread, off the step's critical path. The source must
    not advance its cursor before a fault (true of
    ``CloudDataSetIterator``, whose read precedes the increment), so a
    retried fetch re-reads the same batch and data order is
    preserved."""

    def __init__(self, base: DataSetIterator, policy=None):
        from deeplearning4j_tpu.resilience.retry import RetryPolicy

        self.base = base
        self.policy = policy or RetryPolicy()

    def next(self) -> DataSet:
        from deeplearning4j_tpu.resilience.retry import retry_call

        return retry_call(self.base.next, policy=self.policy)

    def has_next(self) -> bool:
        return self.base.has_next()

    def reset(self) -> None:
        self.base.reset()

    def batch(self) -> int:
        return self.base.batch()

    def total_examples(self) -> int:
        return self.base.total_examples()
