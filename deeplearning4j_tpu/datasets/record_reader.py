"""RecordReader bridge (reference: the DataVec bridge
``RecordReaderDataSetIterator`` / ``SequenceRecordReaderDataSetIterator``
in ``datasets/datavec/``; DataVec itself is an external dependency of
the reference — here a compact host-side equivalent).

``RecordReader`` yields records (lists of values); the iterator turns
them into featurized minibatches with optional one-hot label handling,
mirroring the reference's (labelIndex, numPossibleLabels) contract.
"""

from __future__ import annotations

import csv
import os
from typing import Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator


class RecordReader:
    """SPI: iterable of records (list of str/float)."""

    def records(self) -> Iterator[List]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CSVRecordReader(RecordReader):
    """Reference DataVec ``CSVRecordReader`` (skip lines, delimiter)."""

    def __init__(self, path: str, skip_lines: int = 0,
                 delimiter: str = ","):
        self.path = path
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def records(self) -> Iterator[List]:
        with open(self.path, newline="") as f:
            reader = csv.reader(f, delimiter=self.delimiter)
            for i, row in enumerate(reader):
                if i < self.skip_lines or not row:
                    continue
                yield row


class CollectionRecordReader(RecordReader):
    def __init__(self, collection: Sequence[Sequence]):
        self.collection = collection

    def records(self) -> Iterator[List]:
        return iter([list(r) for r in self.collection])


class RecordReaderDataSetIterator(DataSetIterator):
    """Reference ``RecordReaderDataSetIterator``: featurize records,
    optionally one-hot a label column."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_possible_labels: int = 0,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self._it: Optional[Iterator[List]] = None
        self._pending: Optional[List] = None

    def _ensure(self) -> None:
        if self._it is None:
            self.reader.reset()
            self._it = self.reader.records()
            self._pending = next(self._it, None)

    def has_next(self) -> bool:
        self._ensure()
        return self._pending is not None

    def next(self) -> DataSet:
        self._ensure()
        feats, labels = [], []
        while self._pending is not None and len(feats) < self.batch_size:
            row = [float(v) for v in self._pending]
            self._pending = next(self._it, None)
            if self.label_index is None:
                feats.append(row)
                continue
            label = row[self.label_index]
            row = row[:self.label_index] + row[self.label_index + 1:]
            feats.append(row)
            if self.regression:
                labels.append([label])
            else:
                onehot = [0.0] * self.num_possible_labels
                onehot[int(label)] = 1.0
                labels.append(onehot)
        if not feats:
            raise StopIteration
        x = np.asarray(feats, np.float32)
        y = (np.asarray(labels, np.float32) if labels else x)
        return DataSet(features=x, labels=y)

    def reset(self) -> None:
        self._it = None
        self._pending = None

    def batch(self) -> int:
        return self.batch_size
