"""RecordReader bridge (reference: the DataVec bridge
``RecordReaderDataSetIterator`` / ``SequenceRecordReaderDataSetIterator``
in ``datasets/datavec/``; DataVec itself is an external dependency of
the reference — here a compact host-side equivalent).

``RecordReader`` yields records (lists of values); the iterator turns
them into featurized minibatches with optional one-hot label handling,
mirroring the reference's (labelIndex, numPossibleLabels) contract.
"""

from __future__ import annotations

import csv
import os
from typing import Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator


class RecordReader:
    """SPI: iterable of records (list of str/float)."""

    def records(self) -> Iterator[List]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CSVRecordReader(RecordReader):
    """Reference DataVec ``CSVRecordReader`` (skip lines, delimiter)."""

    def __init__(self, path: str, skip_lines: int = 0,
                 delimiter: str = ","):
        self.path = path
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def records(self) -> Iterator[List]:
        with open(self.path, newline="") as f:
            reader = csv.reader(f, delimiter=self.delimiter)
            for i, row in enumerate(reader):
                if i < self.skip_lines or not row:
                    continue
                yield row


class CollectionRecordReader(RecordReader):
    def __init__(self, collection: Sequence[Sequence]):
        self.collection = collection

    def records(self) -> Iterator[List]:
        return iter([list(r) for r in self.collection])


def _featurize_row(row: List[float], label_index: Optional[int],
                   num_possible_labels: int, regression: bool):
    """Split one numeric record into (features, label_row-or-None) —
    shared by the flat and sequence iterators."""
    if label_index is None:
        return row, None
    label = row[label_index]
    feats = row[:label_index] + row[label_index + 1:]
    if regression:
        return feats, [label]
    onehot = [0.0] * num_possible_labels
    cls = int(label)
    if not 0 <= cls < num_possible_labels:
        raise ValueError(
            f"label {label} outside [0, {num_possible_labels})"
        )
    onehot[cls] = 1.0
    return feats, onehot


class RecordReaderDataSetIterator(DataSetIterator):
    """Reference ``RecordReaderDataSetIterator``: featurize records,
    optionally one-hot a label column."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_possible_labels: int = 0,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self._it: Optional[Iterator[List]] = None
        self._pending: Optional[List] = None

    def _ensure(self) -> None:
        if self._it is None:
            self.reader.reset()
            self._it = self.reader.records()
            self._pending = next(self._it, None)

    def has_next(self) -> bool:
        self._ensure()
        return self._pending is not None

    def next(self) -> DataSet:
        self._ensure()
        feats, labels = [], []
        while self._pending is not None and len(feats) < self.batch_size:
            row = [float(v) for v in self._pending]
            self._pending = next(self._it, None)
            f, l = _featurize_row(
                row, self.label_index, self.num_possible_labels,
                self.regression,
            )
            feats.append(f)
            if l is not None:
                labels.append(l)
        if not feats:
            raise StopIteration
        x = np.asarray(feats, np.float32)
        y = (np.asarray(labels, np.float32) if labels else x)
        return DataSet(features=x, labels=y)

    def reset(self) -> None:
        self._it = None
        self._pending = None

    def batch(self) -> int:
        return self.batch_size


class SequenceRecordReader:
    """SPI: iterable of sequences, each a list of records (reference
    DataVec ``SequenceRecordReader``)."""

    def sequences(self) -> Iterator[List[List]]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CSVSequenceRecordReader(SequenceRecordReader):
    """One CSV file per sequence (reference
    ``CSVSequenceRecordReader`` over a file-per-sequence layout);
    accepts a list of paths or a directory."""

    def __init__(self, paths, skip_lines: int = 0,
                 delimiter: str = ","):
        if isinstance(paths, str):
            paths = sorted(
                os.path.join(paths, n) for n in os.listdir(paths)
                if n.endswith(".csv")
            )
        self.paths = list(paths)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def sequences(self) -> Iterator[List[List]]:
        for p in self.paths:
            with open(p, newline="") as f:
                rows = [
                    row for i, row in enumerate(
                        csv.reader(f, delimiter=self.delimiter)
                    )
                    if i >= self.skip_lines and row
                ]
            yield rows


class CollectionSequenceRecordReader(SequenceRecordReader):
    def __init__(self, sequences: Sequence[Sequence[Sequence]]):
        self._sequences = sequences

    def sequences(self) -> Iterator[List[List]]:
        return iter(
            [[list(r) for r in s] for s in self._sequences]
        )


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Reference ``SequenceRecordReaderDataSetIterator``: sequences ->
    [batch, features, time] tensors with per-timestep labels, padded
    to the batch's longest sequence with masks (the reference's
    variable-length alignment)."""

    def __init__(self, reader: SequenceRecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_possible_labels: int = 0,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self._it: Optional[Iterator] = None
        self._pending = None

    def _ensure(self) -> None:
        if self._it is None:
            self.reader.reset()
            self._it = self.reader.sequences()
            self._pending = next(self._it, None)

    def has_next(self) -> bool:
        self._ensure()
        return self._pending is not None

    def _featurize(self, seq):
        feats, labels = [], []
        for row in seq:
            f, l = _featurize_row(
                [float(v) for v in row], self.label_index,
                self.num_possible_labels, self.regression,
            )
            feats.append(f)
            if l is not None:
                labels.append(l)
        return np.asarray(feats, np.float32), (
            np.asarray(labels, np.float32) if labels else None
        )

    def next(self) -> DataSet:
        self._ensure()
        seqs = []
        while self._pending is not None and len(seqs) < self.batch_size:
            if len(self._pending) == 0:
                raise ValueError(
                    f"sequence {len(seqs)} of this batch is empty "
                    "(zero-length or header-only input)"
                )
            seqs.append(self._featurize(self._pending))
            self._pending = next(self._it, None)
        if not seqs:
            raise StopIteration
        t_max = max(f.shape[0] for f, _ in seqs)
        b = len(seqs)
        n_feat = seqs[0][0].shape[1]
        x = np.zeros((b, n_feat, t_max), np.float32)
        mask = np.zeros((b, t_max), np.float32)
        y = None
        for i, (f, l) in enumerate(seqs):
            t = f.shape[0]
            x[i, :, :t] = f.T
            mask[i, :t] = 1.0
            if l is not None:
                if y is None:
                    y = np.zeros((b, l.shape[1], t_max), np.float32)
                y[i, :, :t] = l.T
        same_len = all(f.shape[0] == t_max for f, _ in seqs)
        return DataSet(
            features=x, labels=(y if y is not None else x),
            features_mask=None if same_len else mask,
            labels_mask=None if same_len or y is None else mask,
        )

    def reset(self) -> None:
        self._it = None
        self._pending = None

    def batch(self) -> int:
        return self.batch_size


class RecordReaderMultiDataSetIterator(DataSetIterator):
    """Reference ``RecordReaderMultiDataSetIterator``: combine named
    readers into MultiDataSets via column-range input/output specs.

    Builder mirror: ``add_reader(name, reader)``, ``add_input(name,
    from_col, to_col)``, ``add_output(name, from_col, to_col)``,
    ``add_output_one_hot(name, col, n_classes)``."""

    def __init__(self, batch_size: int):
        self.batch_size = batch_size
        self._readers: dict = {}
        self._inputs: List[tuple] = []
        self._outputs: List[tuple] = []
        self._iters: Optional[dict] = None
        self._pending: Optional[dict] = None

    def add_reader(self, name: str, reader: RecordReader):
        self._readers[name] = reader
        return self

    def add_input(self, name: str, from_col: int, to_col: int):
        self._inputs.append((name, from_col, to_col, None))
        return self

    def add_output(self, name: str, from_col: int, to_col: int):
        self._outputs.append((name, from_col, to_col, None))
        return self

    def add_output_one_hot(self, name: str, col: int, n_classes: int):
        self._outputs.append((name, col, col, n_classes))
        return self

    def _fetch_row(self):
        """One aligned row from every reader, or None at exhaustion."""
        out = {}
        for n, it in self._iters.items():
            row = next(it, None)
            if row is None:
                return None
            out[n] = [float(v) for v in row]
        return out

    def _ensure(self) -> None:
        if self._iters is None:
            for r in self._readers.values():
                r.reset()
            self._iters = {
                n: r.records() for n, r in self._readers.items()
            }
            # one-row lookahead keeps the has_next contract exact at
            # batch boundaries (same pattern as
            # RecordReaderDataSetIterator._pending)
            self._pending = self._fetch_row()

    def has_next(self) -> bool:
        self._ensure()
        return self._pending is not None

    def next(self):
        from deeplearning4j_tpu.datasets.api import MultiDataSet

        self._ensure()
        if self._pending is None:
            raise StopIteration
        rows: dict = {n: [] for n in self._readers}
        while self._pending is not None and (
            len(next(iter(rows.values()))) < self.batch_size
        ):
            for n, row in self._pending.items():
                rows[n].append(row)
            self._pending = self._fetch_row()

        def slice_cols(spec):
            name, a, b, onehot = spec
            data = np.asarray(rows[name], np.float32)[:, a:b + 1]
            if onehot is not None:
                cls = data[:, 0].astype(int)
                if ((cls < 0) | (cls >= onehot)).any():
                    bad = cls[(cls < 0) | (cls >= onehot)][0]
                    raise ValueError(
                        f"label {bad} outside [0, {onehot}) in "
                        f"reader '{name}' column {a}"
                    )
                out = np.zeros((data.shape[0], onehot), np.float32)
                out[np.arange(data.shape[0]), cls] = 1.0
                return out
            return data

        return MultiDataSet(
            features=[slice_cols(s) for s in self._inputs],
            labels=[slice_cols(s) for s in self._outputs],
        )

    def reset(self) -> None:
        self._iters = None
        self._pending = None

    def batch(self) -> int:
        return self.batch_size
