"""Datasets & iterators (reference: ``deeplearning4j-core`` datasets)."""

from deeplearning4j_tpu.datasets.api import (  # noqa: F401
    DataSet,
    DataSetIterator,
    ExistingDataSetIterator,
    ListDataSetIterator,
    MultiDataSet,
)
