"""Datasets & iterators (reference: ``deeplearning4j-core`` datasets)."""

from deeplearning4j_tpu.datasets.api import (  # noqa: F401
    ChunkedDataSet,
    DataSet,
    DataSetIterator,
    ExistingDataSetIterator,
    ListDataSetIterator,
    MultiDataSet,
    PlacedDataSet,
)
from deeplearning4j_tpu.datasets.prefetch import (  # noqa: F401
    PrefetchIterator,
)
from deeplearning4j_tpu.datasets.validate import (  # noqa: F401
    BatchSchema,
    BatchValidator,
    QuarantineStore,
    ValidatingIterator,
)
from deeplearning4j_tpu.datasets.iterators import (  # noqa: F401
    AsyncDataSetIterator,
    DevicePrefetchIterator,
    INDArrayDataSetIterator,
    MovingWindowDataSetIterator,
    MultipleEpochsIterator,
    ReconstructionDataSetIterator,
    RetryingDataSetIterator,
    SamplingDataSetIterator,
    make_packbits_codec,
)
from deeplearning4j_tpu.datasets.cifar import CifarDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.curves import CurvesDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.iris import IrisDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.lfw import LFWDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.record_reader import (  # noqa: F401
    CSVRecordReader,
    CSVSequenceRecordReader,
    CollectionRecordReader,
    CollectionSequenceRecordReader,
    RecordReader,
    RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator,
    SequenceRecordReader,
    SequenceRecordReaderDataSetIterator,
)
