"""LFW — Labeled Faces in the Wild (reference:
``datasets/iterator/impl/LFWDataSetIterator.java`` over datavec's
``LFWLoader`` with ``ParentPathLabelGenerator``).

Reads any image tree laid out person-per-directory
(``lfw/<person>/<person>_0001.jpg``) via PIL, labels by parent
directory name, resizes to ``img_dim`` and splits train/test by
``split_train_test`` with a seeded shuffle — the same knobs the
reference constructor exposes (batchSize, numExamples, imgDim,
numLabels, train, splitTrainTest, rng).

Resolution order: ``data_dir`` arg, ``DL4J_TPU_LFW_DIR`` env var,
``~/.deeplearning4j_tpu/lfw``. No synthetic fallback — face data can't
be faked meaningfully; missing data raises.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator

HEIGHT, WIDTH, CHANNELS = 250, 250, 3
_EXTS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp")


def _scan(root: str) -> List[Tuple[str, str]]:
    """(path, person) pairs, person = parent directory name."""
    out = []
    for person in sorted(os.listdir(root)):
        pdir = os.path.join(root, person)
        if not os.path.isdir(pdir):
            continue
        for fn in sorted(os.listdir(pdir)):
            if fn.lower().endswith(_EXTS):
                out.append((os.path.join(pdir, fn), person))
    return out


class LFWDataSetIterator(DataSetIterator):
    """Minibatches of face images, one-hot person labels (reference
    ``LFWDataSetIterator.java:1``)."""

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 img_dim: Tuple[int, int, int] = (HEIGHT, WIDTH, CHANNELS),
                 num_labels: Optional[int] = None, train: bool = True,
                 split_train_test: float = 1.0, seed: int = 42,
                 data_dir: Optional[str] = None, flat: bool = False):
        from PIL import Image

        root = (
            data_dir
            or os.environ.get("DL4J_TPU_LFW_DIR")
            or os.path.expanduser("~/.deeplearning4j_tpu/lfw")
        )
        # tolerate the archive's extra nesting (lfw/lfw/<person>/...)
        if os.path.isdir(os.path.join(root, "lfw")):
            root = os.path.join(root, "lfw")
        if not os.path.isdir(root):
            raise FileNotFoundError(
                f"LFW image tree not found at {root!r} (set "
                "DL4J_TPU_LFW_DIR or pass data_dir)."
            )
        entries = _scan(root)
        if not entries:
            raise FileNotFoundError(f"no images found under {root!r}")
        persons = sorted({p for _, p in entries})
        if num_labels is not None and num_labels < len(persons):
            persons = persons[:num_labels]
            keep = set(persons)
            entries = [e for e in entries if e[1] in keep]
        self.labels = persons
        label_idx = {p: i for i, p in enumerate(persons)}

        rng = np.random.RandomState(seed)
        order = rng.permutation(len(entries))
        cut = int(len(entries) * split_train_test)
        sel = order[:cut] if train else order[cut:]
        if num_examples is not None:
            sel = sel[:num_examples]

        # decode lazily per minibatch — the full set at the default
        # 250x250x3 is ~10 GB float32 (reference LFWLoader streams too)
        self._entries = [entries[i] for i in sel]
        self._label_idx = label_idx
        self._img_dim = img_dim
        self._flat = flat
        self.batch_size = batch_size
        self._pos = 0

    def _decode(self, path: str) -> np.ndarray:
        from PIL import Image

        h, w, c = self._img_dim
        img = Image.open(path)
        img = img.convert("RGB" if c == 3 else "L").resize((w, h))
        a = np.asarray(img, np.float32) / 255.0  # [h, w, c?]
        if c == 1:
            a = a[:, :, None]
        return a.transpose(2, 0, 1)

    def next(self) -> DataSet:
        i = self._pos
        j = min(i + self.batch_size, len(self._entries))
        self._pos = j
        chunk = self._entries[i:j]
        h, w, c = self._img_dim
        feats = np.empty((len(chunk), c, h, w), np.float32)
        onehot = np.zeros((len(chunk), len(self.labels)), np.float32)
        for row, (path, person) in enumerate(chunk):
            feats[row] = self._decode(path)
            onehot[row, self._label_idx[person]] = 1.0
        if self._flat:
            feats = feats.reshape(len(feats), -1)
        return DataSet(features=feats, labels=onehot)

    def has_next(self) -> bool:
        return self._pos < len(self._entries)

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self._entries)

    def input_columns(self) -> int:
        h, w, c = self._img_dim
        return c * h * w

    def total_outcomes(self) -> int:
        return len(self.labels)
