"""Framework exception hierarchy (reference
``deeplearning4j-nn/.../exception``: ``DL4JException`` and
subclasses). Raised by configuration validation (residual-width
checks in TransformerBlock/MixtureOfExperts, duplicate layer names);
both config/input subclasses also subclass ValueError so generic
handlers keep working."""


class DL4JException(Exception):
    """Base framework exception (reference ``DL4JException``)."""


class DL4JInvalidConfigException(DL4JException, ValueError):
    """Invalid network configuration (reference
    ``DL4JInvalidConfigException``)."""


class DL4JInvalidInputException(DL4JException, ValueError):
    """Input incompatible with the network (reference
    ``DL4JInvalidInputException``)."""
