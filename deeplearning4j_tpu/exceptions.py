"""Framework exception hierarchy (reference
``deeplearning4j-nn/.../exception``: ``DL4JException`` and
subclasses). Raised by configuration validation (residual-width
checks in TransformerBlock/MixtureOfExperts, duplicate layer names);
both config/input subclasses also subclass ValueError so generic
handlers keep working."""


class DL4JException(Exception):
    """Base framework exception (reference ``DL4JException``)."""


class DL4JInvalidConfigException(DL4JException, ValueError):
    """Invalid network configuration (reference
    ``DL4JInvalidConfigException``)."""


class DL4JInvalidInputException(DL4JException, ValueError):
    """Input incompatible with the network (reference
    ``DL4JInvalidInputException``)."""


class DL4JFaultException(DL4JException):
    """Base for runtime-fault conditions the resilience subsystem
    raises or recovers from (preempted workers, flaky storage,
    corrupted checkpoints, diverged training). Net-new vs the
    reference, whose Spark layer got restartability for free from
    parameter-averaging rounds."""


class CheckpointCorruptedException(DL4JFaultException):
    """A checkpoint failed verification (CRC mismatch, truncated zip,
    missing members) and no earlier version could be restored."""


class CheckpointCommitAbortedException(DL4JFaultException):
    """A sharded checkpoint's two-phase commit aborted: the membership
    the shards were written under changed (a host died or was admitted)
    or the commit barrier was partitioned before rank 0 could write the
    manifest. The uncommitted directory is ignored by restore and
    collected by GC; the previous committed step remains the newest."""


class RetryExhaustedException(DL4JFaultException):
    """A retried operation failed on every attempt of its budget.
    Carries the attempt count and the last underlying cause (also
    chained as ``__cause__``)."""

    def __init__(self, message: str, attempts: int, last_cause: BaseException):
        super().__init__(message)
        self.attempts = attempts
        self.last_cause = last_cause


class CircuitOpenException(DL4JFaultException):
    """A call was rejected because its ``CircuitBreaker`` is open —
    the dependency behind it failed repeatedly and fail-fast beats
    burning a worker on another doomed attempt. ``retry_after`` is
    the seconds until the breaker admits a half-open probe."""

    def __init__(self, message: str, retry_after: float = 0.0):
        super().__init__(message)
        self.retry_after = retry_after


class DeadlineExceededException(DL4JFaultException):
    """A request outlived its deadline (queue wait + execution).
    Carries ``elapsed`` and ``budget`` in seconds. Deliberately NOT a
    ``TimeoutError`` subclass: the default retry allowlist retries
    ``TimeoutError``, and retrying an already-expired budget only
    doubles the damage."""

    def __init__(self, message: str, elapsed: float, budget: float):
        super().__init__(message)
        self.elapsed = elapsed
        self.budget = budget
