"""Device mesh utilities (TPU-native replacement for the reference's
two distribution substrates: ``ParallelWrapper``'s device threads and
Spark's executor topology, SURVEY.md §2.4).

One component replaces both: a ``jax.sharding.Mesh`` over all chips
(ICI within a slice, DCN across slices via ``jax.distributed``), with
named axes — ``data`` for batch sharding (the Spark/ParallelWrapper
analog), ``model`` for tensor parallelism (net-new capability). XLA
inserts the collectives (psum over ICI) that the reference delegates
to Spark RDD aggregation.
"""

from __future__ import annotations

import logging
import math
import os
from typing import Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.exceptions import DL4JFaultException

logger = logging.getLogger(__name__)


def build_mesh(
    data: Optional[int] = None,
    model: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Mesh with (data, model) axes. Defaults: all devices on the data
    axis (pure DP, the reference's only mode)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        if n % model != 0:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(
            f"data({data}) x model({model}) != device count ({n})"
        )
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, axis_names=("data", "model"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis over 'data'."""
    return NamedSharding(mesh, P("data"))


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    timeout_s: Optional[float] = None,
    policy=None,
) -> None:
    """Multi-host initialization (replaces the reference's Spark
    master/executor bootstrap; reference
    ``SparkDl4jMultiLayer``/``TrainingMaster`` setup).

    With no arguments, reads the standard env vars
    (``COORDINATOR_ADDRESS``, ``NUM_PROCESSES``, ``PROCESS_ID``) or
    defers to the TPU pod runtime's automatic configuration.

    ``timeout_s`` (or ``DL4J_TPU_INIT_TIMEOUT_S``) bounds the whole
    bring-up with retry + deadline: a worker that starts before its
    coordinator fails fast with a chained ``DL4JFaultException``
    instead of hanging on jax's 300s default. Without a budget the
    stock blocking call is used unchanged."""
    kwargs = {}
    addr = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if addr:
        kwargs["coordinator_address"] = addr
    npr = num_processes or os.environ.get("NUM_PROCESSES")
    if npr:
        kwargs["num_processes"] = int(npr)
    pid = process_id if process_id is not None else os.environ.get("PROCESS_ID")
    if pid is not None:
        kwargs["process_id"] = int(pid)
    if timeout_s is None:
        env = os.environ.get("DL4J_TPU_INIT_TIMEOUT_S")
        timeout_s = float(env) if env else None
    if timeout_s is None and policy is None:
        jax.distributed.initialize(**kwargs)
        return
    from deeplearning4j_tpu.exceptions import (
        DeadlineExceededException, RetryExhaustedException,
    )
    from deeplearning4j_tpu.resilience.retry import (
        RetryPolicy, retry_call,
    )

    policy = policy or RetryPolicy(
        max_attempts=4, base_delay=0.5, multiplier=2.0, max_delay=5.0,
        retry_on=(OSError, TimeoutError, RuntimeError),
        total_timeout=timeout_s,
    )
    if "coordinator_address" in kwargs and timeout_s is not None:
        # split the budget across attempts so the LAST attempt still
        # gets a slice instead of the first one eating it all
        kwargs["initialization_timeout"] = max(
            1, int(math.ceil(timeout_s / policy.max_attempts)))

    def _attempt():
        try:
            jax.distributed.initialize(**kwargs)
        except RuntimeError as e:
            if "only be called once" in str(e):
                raise DL4JFaultException(
                    "init_distributed: jax.distributed is already "
                    "initialized in this process — call "
                    "shutdown_distributed() before re-forming"
                ) from e
            # drop any half-built client/service so the retry starts
            # from a clean slate
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            raise

    try:
        retry_call(_attempt, policy=policy)
    except (RetryExhaustedException, DeadlineExceededException) as e:
        raise DL4JFaultException(
            "init_distributed: coordinator "
            f"{kwargs.get('coordinator_address', '<auto>')} not "
            f"reachable within {timeout_s}s — start the coordinator "
            "first, or raise timeout_s / DL4J_TPU_INIT_TIMEOUT_S"
        ) from e


def _enable_cpu_collectives() -> None:
    """Cross-process collectives on the CPU backend need the gloo
    implementation (the default 'none' fails every multi-process
    computation outright). Harmless on TPU; skipped when already
    chosen or when this jax predates the flag."""
    try:
        current = jax.config.jax_cpu_collectives_implementation
    except AttributeError:
        current = None
    try:
        if current in (None, "none"):
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass


def init_distributed_elastic(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    *,
    timeout_s: float = 30.0,
    policy=None,
    heartbeat_interval_s: int = 1,
    max_missing_heartbeats: int = 10,
    shutdown_timeout_s: int = 3,
    on_peer_failure: Optional[Callable] = None,
) -> None:
    """Survivor-safe ``jax.distributed`` bring-up for the cross-host
    control plane. Builds the coordination client/service directly
    (same wire protocol as ``jax.distributed.initialize``) so that a
    host-loss survivor can actually outlive its peers:

    - a peer-failure notice runs ``on_peer_failure`` (default: log +
      flight-recorder event) instead of the stock client's
      LOG(QFATAL) process kill;
    - the shutdown barrier is bounded (``shutdown_timeout_s``), so a
      survivor's teardown cannot hang on a SIGKILLed peer that will
      never arrive;
    - connection is bounded-retried like :func:`init_distributed`.

    Pair with :func:`shutdown_distributed` + :func:`reform_distributed`
    for the teardown/re-formation cycle."""
    from jax._src import distributed as _jdist

    import jaxlib.xla_extension as xe

    from deeplearning4j_tpu.observability import flightrec
    from deeplearning4j_tpu.exceptions import (
        DeadlineExceededException, RetryExhaustedException,
    )
    from deeplearning4j_tpu.resilience.retry import (
        RetryPolicy, retry_call,
    )

    state = _jdist.global_state
    if state.client is not None:
        raise DL4JFaultException(
            "init_distributed_elastic: a distributed client is still "
            "live — call shutdown_distributed() first")
    _enable_cpu_collectives()

    def _notice(*args):
        logger.warning("jax coordination peer-failure notice: %s",
                       args)
        flightrec.record_event("jax_peer_failure",
                               detail=str(args)[:200])
        if on_peer_failure is not None:
            on_peer_failure(*args)

    policy = policy or RetryPolicy(
        max_attempts=3, base_delay=0.5, max_delay=3.0,
        retry_on=(OSError, TimeoutError, RuntimeError),
        total_timeout=timeout_s,
    )
    per_attempt = max(1, int(math.ceil(timeout_s / policy.max_attempts)))
    port = coordinator_address.rsplit(":", 1)[1]

    def _attempt():
        if process_id == 0 and state.service is None:
            # the service survives a failed client attempt: it is
            # already listening and the next attempt connects to it
            state.service = xe.get_distributed_runtime_service(
                "[::]:" + port, num_processes,
                heartbeat_interval=heartbeat_interval_s,
                max_missing_heartbeats=max_missing_heartbeats,
                shutdown_timeout=shutdown_timeout_s,
            )
        client = xe.get_distributed_runtime_client(
            coordinator_address, process_id,
            init_timeout=per_attempt,
            shutdown_timeout=shutdown_timeout_s,
            heartbeat_interval=heartbeat_interval_s,
            max_missing_heartbeats=max_missing_heartbeats,
            missed_heartbeat_callback=_notice,
            shutdown_on_destruction=False,
            use_compression=True,
        )
        client.connect()
        state.client = client
        state.process_id = process_id
        state.num_processes = num_processes
        state.coordinator_address = coordinator_address

    try:
        retry_call(_attempt, policy=policy)
    except (RetryExhaustedException, DeadlineExceededException) as e:
        raise DL4JFaultException(
            "init_distributed_elastic: could not form a "
            f"{num_processes}-process runtime at "
            f"{coordinator_address} within {timeout_s}s"
        ) from e


def shutdown_distributed() -> None:
    """Tear down the jax distributed runtime AND the backend registry
    so this process can re-initialize over a new process set (host-loss
    mesh re-formation). Never raises: a failing shutdown barrier (dead
    peers cannot arrive at it) is logged and abandoned — bounded only
    when the runtime came from :func:`init_distributed_elastic`, whose
    client has a small shutdown timeout and a benign failure
    callback."""
    from jax._src import distributed as _jdist

    state = _jdist.global_state
    if state.client is not None:
        try:
            state.client.shutdown()
        except Exception as e:
            logger.warning(
                "distributed client shutdown abandoned: %r", e)
        state.client = None
    if state.service is not None:
        try:
            state.service.shutdown()
        except Exception as e:
            logger.warning(
                "distributed service shutdown abandoned: %r", e)
        state.service = None
    state.preemption_sync_manager = None
    state.process_id = 0
    state.num_processes = 1
    state.coordinator_address = None
    import jax.extend.backend as _jeb

    _jeb.clear_backends()


def reform_distributed(plan, *, data: Optional[int] = None,
                       model: int = 1,
                       timeout_s: float = 30.0) -> Mesh:
    """One call from recovery plan to fresh mesh: tear down the old
    runtime, re-initialize over the survivor set named by ``plan`` (a
    ``control_plane.RecoveryPlan`` or any object/dict with
    ``jax_coordinator`` / ``num`` / ``rank``), return a mesh over the
    new global device set."""
    if isinstance(plan, dict):
        get = plan.get
    else:
        def get(k, d=None):
            return getattr(plan, k, d)

    addr = get("jax_coordinator")
    num = int(get("num"))
    rank = int(get("rank"))
    if addr is None:
        raise DL4JFaultException(
            "reform_distributed: plan has no jax_coordinator address")
    shutdown_distributed()
    init_distributed_elastic(addr, num, rank, timeout_s=timeout_s)
    return build_mesh(data=data, model=model)


def process_local_batch(global_batch: int, mesh: Mesh) -> int:
    """Per-host share of a global batch (host-sharded input pipeline,
    the AsyncDataSetIterator-per-executor analog), proportional to the
    mesh devices this process owns."""
    devices = list(mesh.devices.flat)
    local = sum(
        1 for d in devices if d.process_index == jax.process_index()
    )
    return global_batch * local // len(devices)
