"""Device mesh utilities (TPU-native replacement for the reference's
two distribution substrates: ``ParallelWrapper``'s device threads and
Spark's executor topology, SURVEY.md §2.4).

One component replaces both: a ``jax.sharding.Mesh`` over all chips
(ICI within a slice, DCN across slices via ``jax.distributed``), with
named axes — ``data`` for batch sharding (the Spark/ParallelWrapper
analog), ``model`` for tensor parallelism (net-new capability). XLA
inserts the collectives (psum over ICI) that the reference delegates
to Spark RDD aggregation.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def build_mesh(
    data: Optional[int] = None,
    model: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Mesh with (data, model) axes. Defaults: all devices on the data
    axis (pure DP, the reference's only mode)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        if n % model != 0:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(
            f"data({data}) x model({model}) != device count ({n})"
        )
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, axis_names=("data", "model"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis over 'data'."""
    return NamedSharding(mesh, P("data"))


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host initialization (replaces the reference's Spark
    master/executor bootstrap; reference
    ``SparkDl4jMultiLayer``/``TrainingMaster`` setup).

    With no arguments, reads the standard env vars
    (``COORDINATOR_ADDRESS``, ``NUM_PROCESSES``, ``PROCESS_ID``) or
    defers to the TPU pod runtime's automatic configuration.
    """
    kwargs = {}
    addr = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if addr:
        kwargs["coordinator_address"] = addr
    npr = num_processes or os.environ.get("NUM_PROCESSES")
    if npr:
        kwargs["num_processes"] = int(npr)
    pid = process_id if process_id is not None else os.environ.get("PROCESS_ID")
    if pid is not None:
        kwargs["process_id"] = int(pid)
    jax.distributed.initialize(**kwargs)


def process_local_batch(global_batch: int, mesh: Mesh) -> int:
    """Per-host share of a global batch (host-sharded input pipeline,
    the AsyncDataSetIterator-per-executor analog), proportional to the
    mesh devices this process owns."""
    devices = list(mesh.devices.flat)
    local = sum(
        1 for d in devices if d.process_index == jax.process_index()
    )
    return global_batch * local // len(devices)
