"""Elastic data-parallel training: heartbeat liveness, host-RAM
snapshot ring, survivor-mesh recovery, straggler detection.

A lost device kills a ``jax.sharding.Mesh`` program outright — the
collective hangs, the run dies, and everything since the last
published checkpoint is gone. The reference got elasticity for free
from Spark (a lost worker's partition is just re-run); this module is
the per-step-training equivalent, and it deliberately recovers
WITHOUT disk I/O:

- :class:`HeartbeatMonitor` — per-shard per-step heartbeats with a
  timeout: a shard that stops beating for ``timeout`` seconds is
  declared dead (``heartbeat_missed_total{shard=}``). Chaos tests
  inject death directly via :meth:`HeartbeatMonitor.mark_dead`.
- :class:`SnapshotRing` — a small ring of full training snapshots
  (params / updater state / layer state / RNG base key / step) copied
  to host RAM every K steps. Recovery restores from the newest ring
  entry: no object store round-trip inside the grace window, and the
  run loses at most ``snapshot_every - 1`` steps.
- :class:`ElasticTrainer` — wraps :class:`~.trainer.
  DistributedTrainer`; on declared death it rebuilds the mesh over
  the SURVIVING devices (``build_mesh(devices=survivors)``),
  re-places params/updater/state with the survivor shardings, rolls
  the model back to the newest snapshot, and resumes — the batch
  re-shards automatically through ``place_minibatch`` (pad-and-mask
  handles non-divisible batches). Trajectory stays exact: the
  restored step counter re-derives the same per-step PRNG folds and
  lr schedules the uninterrupted run would have used.
- :class:`StragglerDetector` — per-shard step-time EWMA; a shard
  whose EWMA exceeds ``factor`` x the median of its peers' is
  flagged (``straggler_detected_total{shard=}``) so operators see a
  slow host before it becomes a dead one.

Elasticity is data-parallel only: tensor-parallel weight shards on a
dead device have no replica to recover from (the snapshot ring would
be the only copy — that is a checkpoint-restore scenario, not an
elastic one), so ``tensor_parallel=True`` is rejected up front.
"""

from __future__ import annotations

import collections
import logging
import random
import time
import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax

from deeplearning4j_tpu.exceptions import DL4JFaultException
from deeplearning4j_tpu.parallel.mesh import build_mesh
from deeplearning4j_tpu.parallel.trainer import DistributedTrainer

logger = logging.getLogger(__name__)


def _default_registry():
    from deeplearning4j_tpu.observability.metrics import default_registry

    return default_registry()


class DeviceLostException(DL4JFaultException):
    """A shard was declared dead and recovery was impossible (no
    snapshot, or no survivors left)."""

    def __init__(self, message: str, dead: Sequence[str] = ()):
        super().__init__(message)
        self.dead = tuple(dead)


class HeartbeatMonitor:
    """Liveness ledger: every shard must beat every step; silence
    past ``timeout`` seconds means dead. The clock is injectable so
    tests advance time manually instead of sleeping.

    ``epoch`` tracks the control-plane membership epoch the ledger
    belongs to: ``reset`` advances it, and :meth:`clear` un-declares a
    shard only when the caller proves it holds the CURRENT epoch — a
    rejoined member is welcomed back, a zombie clearing itself with a
    stale epoch is not."""

    def __init__(self, shards: Sequence[str], timeout: float = 30.0,
                 clock=time.monotonic, jitter: float = 0.0,
                 seed: Optional[int] = None, registry=None):
        if timeout <= 0:
            raise ValueError("heartbeat timeout must be > 0")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.timeout = float(timeout)
        self.clock = clock
        self.jitter = float(jitter)
        self.epoch = 0
        registry = registry if registry is not None else _default_registry()
        self._m_missed = registry.counter(
            "heartbeat_missed_total",
            help="shards declared dead after a heartbeat timeout",
            labels=("shard",),
        )
        self._seed = 0 if seed is None else int(seed)
        self._rngs: Dict[str, random.Random] = {}
        self._last: Dict[str, float] = {}
        self._declared: set = set()
        self._counted: set = set()
        self.reset(shards)

    def reset(self, shards: Sequence[str]) -> None:
        """Restart the ledger over ``shards`` (post-recovery: the
        survivor set) and advance the epoch. Everyone gets a fresh
        grace period."""
        now = self.clock()
        self._last = {str(s): now for s in shards}
        self._declared = set()
        self._counted = set()
        self.epoch += 1
        # per-shard rng seeded by (seed, shard id): each shard's beat
        # cadence decorrelates from its peers' (the
        # ServingRouter.health_jitter pattern) so a fleet's renewals
        # don't synchronize into thundering-herd bursts; crc32, not
        # hash() — the latter is salted per process
        self._rngs = {s: random.Random(self._shard_seed(s))
                      for s in self._last}

    def _shard_seed(self, shard: str) -> int:
        return (self._seed << 32) ^ zlib.crc32(str(shard).encode())

    def next_interval(self, shard) -> float:
        """The shard's next beat interval: a third of the timeout,
        jittered by its own seeded rng. Deterministic per (seed,
        shard) — two ranks never share a schedule."""
        s = str(shard)
        rng = self._rngs.get(s)
        if rng is None:
            raise KeyError(f"unknown shard {s!r}")
        base = self.timeout / 3.0
        if self.jitter <= 0.0:
            return base
        return base * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))

    def clear(self, shard, epoch: int) -> bool:
        """Epoch-fenced un-declare: a member readmitted at control
        epoch ``epoch`` stops being sticky-dead — but only if that IS
        the current epoch (a zombie's stale clear is refused).
        Returns whether the shard is alive afterwards."""
        s = str(shard)
        if int(epoch) != self.epoch:
            logger.warning(
                "heartbeat clear refused for shard %s: epoch %d != "
                "current %d", s, int(epoch), self.epoch)
            return False
        self._declared.discard(s)
        self._counted.discard(s)
        self._last[s] = self.clock()
        self._rngs.setdefault(s, random.Random(self._shard_seed(s)))
        return True

    @property
    def shards(self) -> List[str]:
        return list(self._last)

    def beat(self, shard, step: Optional[int] = None) -> None:
        """Record a heartbeat. Beats from an already-declared-dead
        shard are ignored: death is sticky until ``reset`` (a zombie
        host must not rejoin mid-mesh)."""
        s = str(shard)
        if s in self._declared:
            return
        if s not in self._last:
            raise KeyError(f"unknown shard {s!r}")
        self._last[s] = self.clock()

    def mark_dead(self, shard) -> None:
        """Chaos injection: declare ``shard`` dead immediately (the
        simulated device loss, equivalent to its heartbeats timing
        out)."""
        s = str(shard)
        if s not in self._last:
            raise KeyError(f"unknown shard {s!r}")
        self._declared.add(s)

    def dead(self) -> List[str]:
        """Shards currently declared dead (injected or timed out).
        First transition of each shard increments
        ``heartbeat_missed_total{shard=}``."""
        now = self.clock()
        out = set(self._declared)
        for s, t in self._last.items():
            if now - t >= self.timeout:
                out.add(s)
        for s in out - self._counted:
            self._counted.add(s)
            self._m_missed.labels(s).inc()
            logger.warning("shard %s declared dead (no heartbeat)", s)
        return sorted(out)

    def alive(self) -> List[str]:
        gone = set(self.dead())
        return [s for s in self._last if s not in gone]


class StragglerDetector:
    """Per-shard step-time EWMA -> straggler flag. A shard is a
    straggler while its EWMA exceeds ``factor`` x the median of the
    OTHER shards' EWMAs (after ``warmup`` observations each);
    entering the state increments ``straggler_detected_total``."""

    def __init__(self, alpha: float = 0.3, factor: float = 2.0,
                 warmup: int = 3, registry=None):
        if not 0 < alpha <= 1:
            raise ValueError("alpha must be in (0, 1]")
        if factor <= 1:
            raise ValueError("factor must be > 1")
        self.alpha = float(alpha)
        self.factor = float(factor)
        self.warmup = int(warmup)
        registry = registry if registry is not None else _default_registry()
        self._m_straggler = registry.counter(
            "straggler_detected_total",
            help="shards whose step-time EWMA exceeded factor x the "
                 "median of their peers'",
            labels=("shard",),
        )
        self._ewma: Dict[str, float] = {}
        self._n: Dict[str, int] = collections.defaultdict(int)
        self._flagged: set = set()

    def observe(self, shard, step_time_s: float) -> None:
        s = str(shard)
        prev = self._ewma.get(s)
        self._ewma[s] = (
            step_time_s if prev is None
            else self.alpha * step_time_s + (1 - self.alpha) * prev
        )
        self._n[s] += 1

    def ewma(self, shard) -> Optional[float]:
        return self._ewma.get(str(shard))

    def stragglers(self) -> List[str]:
        """Current stragglers; transitions into the state count."""
        warm = {s: v for s, v in self._ewma.items()
                if self._n[s] >= self.warmup}
        current = set()
        if len(warm) >= 2:
            for s, v in warm.items():
                peers = [w for p, w in warm.items() if p != s]
                if v > self.factor * float(np.median(peers)):
                    current.add(s)
        for s in sorted(current - self._flagged):
            self._m_straggler.labels(s).inc()
            logger.warning("shard %s is straggling (ewma %.4fs)",
                           s, self._ewma[s])
        self._flagged = current
        return sorted(current)

    def forget(self, shard) -> None:
        """Drop a shard's history (post-recovery: it left the mesh)."""
        s = str(shard)
        self._ewma.pop(s, None)
        self._n.pop(s, None)
        self._flagged.discard(s)


class SnapshotRing:
    """Bounded ring of host-RAM training snapshots. Each ``push``
    copies params / updater state / layer state / the PRNG base key /
    step + epoch counters off-device into fresh numpy arrays — the
    ring shares no buffers with the live model, so a post-snapshot
    update can never corrupt a recovery point. Recovery is
    ``restore_into_model`` + re-placement by the new trainer: zero
    disk I/O."""

    def __init__(self, capacity: int = 2, registry=None):
        if capacity < 1:
            raise ValueError("ring capacity must be >= 1")
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity
        )
        registry = registry if registry is not None else _default_registry()
        self._m_saves = registry.counter(
            "snapshot_ring_saves_total",
            help="host-RAM recovery snapshots taken",
        )._default()

    @staticmethod
    def _host(tree):
        # buffer-isolated host copies; shared with write-behind
        # checkpoint snapshots (core.host_snapshot_tree), so
        # cross-process-sharded leaves gather correctly too
        from deeplearning4j_tpu.nn import core

        return core.host_snapshot_tree(tree)

    def push(self, model, epoch_index: int = 0) -> dict:
        """Snapshot ``model`` at its current step. ``epoch_index``
        is the batch index within the current epoch (so the fit loop
        can replay from the right batch after a rollback).

        ZeRO-sharded updater state (``model._zero_layout``) is
        gathered to its canonical shapes first — the ring holds ONE
        host copy of each shard, never N padded replicas, and the
        snapshot re-shards cleanly onto whatever mesh recovery
        builds."""
        from deeplearning4j_tpu.nn import core

        upd = model.updater_state
        if getattr(model, "_zero_layout", None):
            upd = core.zero_gather_updater_state(upd, model.params)
        snap = {
            "step": int(model.iteration_count),
            "epoch": int(model.epoch_count),
            "epoch_index": int(epoch_index),
            "params": self._host(model.params),
            "updater_state": self._host(upd),
            "state": self._host(model.state),
            "rng": np.array(model._base_key),
        }
        self._ring.append(snap)
        self._m_saves.inc()
        return snap

    def latest(self) -> Optional[dict]:
        return self._ring[-1] if self._ring else None

    def __len__(self) -> int:
        return len(self._ring)

    def restore_into_model(self, model) -> dict:
        """Roll ``model`` back to the newest snapshot (host arrays;
        the caller re-places them on its mesh). Raises
        ``DeviceLostException`` when the ring is empty."""
        snap = self.latest()
        if snap is None:
            raise DeviceLostException(
                "no recovery snapshot in the ring"
            )
        model.params = self._host(snap["params"])
        model.updater_state = self._host(snap["updater_state"])
        model.state = self._host(snap["state"])
        # ring snapshots are canonical-shaped: any ZeRO flat layout is
        # gone until the next trainer re-places (and re-shards) state
        if getattr(model, "_zero_layout", None):
            model._zero_layout = None
        model._base_key = jax.numpy.asarray(snap["rng"])
        model.iteration_count = snap["step"]
        model.epoch_count = snap["epoch"]
        return snap


class ElasticTrainer:
    """Data-parallel training that survives device loss (module
    docstring). Wraps a :class:`DistributedTrainer`; drives the same
    ``fit_minibatch`` hot path, adding per-step heartbeats, periodic
    host-RAM snapshots, straggler EWMAs, and — on a declared death —
    survivor-mesh rebuild + snapshot rollback + replay.

    ``fit`` materializes the iterator (elastic replay needs random
    access to the current epoch's batches); streams that cannot be
    materialized belong to the checkpoint-resume path instead.
    """

    def __init__(self, model, mesh=None, *, snapshot_every: int = 8,
                 ring_capacity: int = 2, heartbeat_timeout: float = 30.0,
                 straggler_factor: float = 2.0, clock=time.monotonic,
                 registry=None, **trainer_kwargs):
        if trainer_kwargs.get("tensor_parallel"):
            raise ValueError(
                "ElasticTrainer is data-parallel only: a dead "
                "device's tensor-parallel weight shard has no "
                "surviving replica (use checkpoint restore instead)"
            )
        self.model = model
        self.clock = clock
        self._trainer_kwargs = dict(trainer_kwargs)
        self.trainer = DistributedTrainer(model, mesh=mesh,
                                          **self._trainer_kwargs)
        self.snapshot_every = max(int(snapshot_every), 1)
        registry = registry if registry is not None else _default_registry()
        self.ring = SnapshotRing(ring_capacity, registry=registry)
        self.monitor = HeartbeatMonitor(
            self._shard_names(), timeout=heartbeat_timeout,
            clock=clock, registry=registry,
        )
        self.straggler = StragglerDetector(
            factor=straggler_factor, registry=registry,
        )
        self._m_recoveries = registry.counter(
            "elastic_recoveries_total",
            help="survivor-mesh recoveries after device loss",
        )._default()
        self._m_recovery_ms = registry.summary(
            "elastic_recovery_ms",
            help="device-loss recovery latency: snapshot rollback + "
                 "survivor-mesh rebuild + re-placement (ms)",
        )._default()
        self._m_devices = registry.gauge(
            "elastic_mesh_devices",
            help="devices in the current training mesh",
        )._default()
        self._m_devices.set(len(self.devices()))
        self.recoveries = 0

    # -- mesh introspection ---------------------------------------------

    @property
    def mesh(self):
        return self.trainer.mesh

    def devices(self) -> list:
        return list(self.trainer.mesh.devices.flat)

    def _shard_names(self) -> List[str]:
        return [str(d.id) for d in self.devices()]

    # -- chaos hooks ----------------------------------------------------

    def inject_device_loss(self, shards) -> None:
        """Chaos: declare the given shard ids (device ids or their
        string names) dead — the next step boundary recovers onto
        the survivors."""
        for s in shards:
            self.monitor.mark_dead(s)

    # -- recovery -------------------------------------------------------

    def recover(self, dead: Sequence[str]) -> dict:
        """Roll back to the newest snapshot and rebuild over the
        survivors. Returns the snapshot used. Raises
        ``DeviceLostException`` when nothing survives or no snapshot
        exists."""
        t0 = self.clock()
        dead = {str(s) for s in dead}
        survivors = [d for d in self.devices()
                     if str(d.id) not in dead]
        if not survivors:
            raise DeviceLostException(
                f"all {len(dead)} shards lost; nothing to rebuild on",
                dead=sorted(dead),
            )
        snap = self.ring.restore_into_model(self.model)
        new_mesh = build_mesh(data=len(survivors), model=1,
                              devices=survivors)
        # a fresh DistributedTrainer re-derives the survivor
        # shardings and re-places params/updater/state (the broadcast
        # step); the jitted steps rebuild lazily on first use
        self.trainer = DistributedTrainer(self.model, mesh=new_mesh,
                                          **self._trainer_kwargs)
        for s in dead:
            self.straggler.forget(s)
        self.monitor.reset(self._shard_names())
        self.recoveries += 1
        self._m_recoveries.inc()
        self._m_devices.set(len(survivors))
        self._m_recovery_ms.observe((self.clock() - t0) * 1000.0)
        logger.warning(
            "recovered from loss of %s: %d survivors, rolled back to "
            "step %d", sorted(dead), len(survivors), snap["step"],
        )
        return snap

    # -- the elastic fit loop -------------------------------------------

    def fit(self, batches, epochs: int = 1) -> list:
        """Fit ``epochs`` passes over ``batches`` (materialized), one
        optimizer step per batch, with liveness + snapshots at every
        step boundary. Returns per-epoch mean scores, matching
        ``DistributedTrainer.fit``."""
        from deeplearning4j_tpu.resilience import preemption

        batches = list(batches)
        m = self.model
        epoch_scores = []
        for _ in range(epochs):
            for listener in m.listeners:
                if hasattr(listener, "on_epoch_start"):
                    listener.on_epoch_start(m)
            scores: Dict[int, float] = {}
            i = 0
            steps_since_snap = None  # force a snapshot at epoch start
            while i < len(batches):
                preemption.check_fit(m)
                if steps_since_snap is None or (
                    steps_since_snap >= self.snapshot_every
                ):
                    self.ring.push(m, epoch_index=i)
                    steps_since_snap = 0
                dead = self.monitor.dead()
                if dead:
                    snap = self.recover(dead)
                    i = snap["epoch_index"]
                    scores = {k: v for k, v in scores.items() if k < i}
                    steps_since_snap = 0
                    continue
                t0 = self.clock()
                scores[i] = self.trainer.fit_minibatch(batches[i])
                dt = self.clock() - t0
                for s in self.monitor.shards:
                    self.monitor.beat(s)
                    self.straggler.observe(s, dt)
                self.straggler.stragglers()
                steps_since_snap += 1
                i += 1
            vals = [scores[k] for k in sorted(scores)]
            epoch_scores.append(
                float(np.mean([float(v) for v in vals]))
                if vals else float("nan")
            )
            for listener in m.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(m)
            m.epoch_count += 1
        return epoch_scores


class HostElasticTrainer:
    """Cross-HOST elastic training: one of these per worker process,
    driven by a ``control_plane.WorkerAgent``. Extends the
    :class:`ElasticTrainer` recipe from device loss inside one
    process to the loss of a whole process:

    - every step ends at a coordinator **barrier** (which doubles as
      a lease renewal), so all survivors agree on the recovery point;
    - every K steps each worker pushes a host-RAM snapshot — in
      lockstep, because the barrier keeps step counters aligned;
    - when the barrier returns a :class:`~.control_plane.RecoveryPlan`
      (a peer's lease expired, or a member was admitted), recovery
      runs: adopt the plan (renewals continue under the new epoch
      while we rebuild), tear down + re-form the jax runtime over the
      survivor set (``mesh.reform_distributed`` — new term, fresh
      port), roll back to the newest ring snapshot, and hand the
      restored canonical state to a fresh
      :class:`~.trainer.DistributedTrainer`, which re-places — and
      for ZeRO, re-shards — it onto the smaller mesh;
    - **coordinator loss** degrades gracefully: checkpoint (when a
      manager is configured) and raise a ``PreemptedException`` so
      ``exit_on_preemption`` exits 75/76 instead of hanging;
    - a **fence** (this host was declared dead but is actually alive,
      e.g. un-partitioned) propagates: zombie state must not be
      checkpointed.

    Trajectory equivalence is the same piecewise claim as
    ``ElasticTrainer``, proven bitwise in
    ``tests/test_control_plane.py``'s real 2-process SIGKILL storm:
    full-width to the snapshot, survivor-width after."""

    def __init__(self, model, agent, *, mesh=None,
                 snapshot_every: int = 8, ring_capacity: int = 2,
                 checkpoint_manager=None, reform=None,
                 reform_timeout_s: float = 30.0, clock=time.monotonic,
                 registry=None, **trainer_kwargs):
        if trainer_kwargs.get("tensor_parallel"):
            raise ValueError(
                "HostElasticTrainer is data-parallel only: a dead "
                "host's tensor-parallel weight shard has no "
                "surviving replica (use checkpoint restore instead)"
            )
        self.model = model
        self.agent = agent
        self.clock = clock
        self._trainer_kwargs = dict(trainer_kwargs)
        self.trainer = DistributedTrainer(model, mesh=mesh,
                                          **self._trainer_kwargs)
        self.snapshot_every = max(int(snapshot_every), 1)
        registry = registry if registry is not None else _default_registry()
        self.ring = SnapshotRing(ring_capacity, registry=registry)
        self.manager = checkpoint_manager
        self._reform = reform
        self.reform_timeout_s = float(reform_timeout_s)
        self._m_recoveries = registry.counter(
            "host_recoveries_total",
            help="host-loss recoveries: mesh re-formed over the "
                 "survivor process set",
        )._default()
        self._m_recovery_ms = registry.summary(
            "host_recovery_ms",
            help="host-loss recovery latency: runtime re-formation + "
                 "snapshot rollback + re-placement (ms)",
        )._default()
        self.recoveries = 0
        self.last_recovery: Optional[dict] = None
        self.last_recovery_snapshot: Optional[dict] = None

    @property
    def mesh(self):
        return self.trainer.mesh

    # -- recovery --------------------------------------------------------

    def _reform_mesh(self, plan):
        if self._reform is not None:
            return self._reform(plan)
        from deeplearning4j_tpu.parallel.mesh import reform_distributed

        return reform_distributed(plan, data=None, model=1,
                                  timeout_s=self.reform_timeout_s)

    def recover(self, plan) -> dict:
        """Execute a recovery plan: new epoch adopted first (so the
        renewal thread keeps the lease alive under the new epoch while
        the runtime re-forms), then runtime re-formation, then ring
        rollback + fresh trainer. Returns the snapshot restored."""
        from deeplearning4j_tpu.observability import flightrec
        from deeplearning4j_tpu.observability.trace import get_tracer

        t0 = self.clock()
        with get_tracer().start_span(
                "control.host_recover",
                attrs={"epoch": plan.epoch,
                       "survivors": plan.num}) as span:
            self.agent.adopt(plan)
            new_mesh = self._reform_mesh(plan)
            snap = self.ring.restore_into_model(self.model)
            self.trainer = DistributedTrainer(
                self.model, mesh=new_mesh, **self._trainer_kwargs)
            span.set_attr("rolled_back_to", snap["step"])
        dt_ms = (self.clock() - t0) * 1000.0
        self.recoveries += 1
        self._m_recoveries.inc()
        self._m_recovery_ms.observe(dt_ms)
        self.last_recovery = {
            "epoch": plan.epoch, "term": plan.term,
            "dead": list(plan.dead), "admitted": list(plan.admitted),
            "survivors": plan.num,
            "rolled_back_to": snap["step"],
        }
        self.last_recovery_snapshot = snap
        flightrec.record_event(
            "host_recovery", epoch=plan.epoch, dead=list(plan.dead),
            survivors=plan.num, rolled_back_to=snap["step"],
            ms=round(dt_ms, 3))
        logger.warning(
            "host recovery: epoch %d, dead=%s, %d survivors, rolled "
            "back to step %d in %.0fms", plan.epoch, list(plan.dead),
            plan.num, snap["step"], dt_ms)
        return snap

    def _coordinator_lost(self, step: int, cause) -> None:
        """Membership truth is gone: checkpoint what we have and exit
        through the preemption machinery (75 with a checkpoint, 76
        without) instead of hanging or training a partitioned
        brain."""
        from deeplearning4j_tpu.observability import flightrec
        from deeplearning4j_tpu.resilience.preemption import (
            PreemptedException,
        )

        info = None
        failed = False
        if self.manager is not None:
            try:
                info = self.manager.save(self.model)
            except Exception as e:
                failed = True
                logger.error(
                    "coordinator lost AND the exit checkpoint "
                    "failed: %r", e)
        flightrec.record_event("coordinator_lost", step=int(step),
                               checkpointed=info is not None)
        raise PreemptedException(
            f"control coordinator lost at step {step}; "
            + ("checkpoint saved" if info is not None
               else "no checkpoint manager configured" if not failed
               else "checkpoint FAILED"),
            step=int(step), checkpoint=info, checkpoint_failed=failed,
            reason="coordinator-lost",
        ) from cause

    def _step_barrier(self, step: int):
        from deeplearning4j_tpu.parallel.control_plane import (
            CoordinatorLostException,
        )

        try:
            return self.agent.step_barrier(step)
        except CoordinatorLostException as e:
            self._coordinator_lost(step, e)
        # HostFencedException propagates: zombie state stays un-saved

    # -- the cross-host fit loop ----------------------------------------

    def fit(self, batches, epochs: int = 1) -> list:
        """Fit ``epochs`` passes over ``batches`` (materialized), one
        optimizer step per batch, a coordinator barrier at every step
        boundary, a lockstep snapshot every ``snapshot_every`` steps.
        Returns per-epoch mean scores, matching
        ``DistributedTrainer.fit``."""
        from deeplearning4j_tpu.parallel import control_plane
        from deeplearning4j_tpu.resilience import preemption

        batches = list(batches)
        m = self.model
        epoch_scores = []
        control_plane.install_agent(self.agent)
        try:
            for _ in range(epochs):
                for listener in m.listeners:
                    if hasattr(listener, "on_epoch_start"):
                        listener.on_epoch_start(m)
                scores: Dict[int, float] = {}
                i = 0
                steps_since_snap = None  # snapshot at epoch start
                while i < len(batches):
                    preemption.check_fit(m)
                    if steps_since_snap is None or (
                        steps_since_snap >= self.snapshot_every
                    ):
                        self.ring.push(m, epoch_index=i)
                        steps_since_snap = 0
                    plan = self._step_barrier(m.iteration_count)
                    if plan is not None:
                        snap = self.recover(plan)
                        i = snap["epoch_index"]
                        scores = {k: v for k, v in scores.items()
                                  if k < i}
                        steps_since_snap = 0
                        continue
                    scores[i] = self.trainer.fit_minibatch(batches[i])
                    steps_since_snap += 1
                    i += 1
                vals = [scores[k] for k in sorted(scores)]
                epoch_scores.append(
                    float(np.mean([float(v) for v in vals]))
                    if vals else float("nan")
                )
                for listener in m.listeners:
                    if hasattr(listener, "on_epoch_end"):
                        listener.on_epoch_end(m)
                m.epoch_count += 1
        finally:
            control_plane.uninstall_agent(self.agent)
        return epoch_scores
