"""jax version-compat shims shared by every parallel module.

The public home of the ``shard_map`` wrapper previously tucked into
``sequence.py`` — trainer, pipeline, expert, the profile scripts and
``__graft_entry__`` all depend on it, so it lives here rather than
inside the ring-attention module.
"""

from __future__ import annotations

import jax


def shard_map_compat():
    """shard_map across jax versions: >=0.8 renamed ``check_rep`` to
    ``check_vma`` and moved the function out of ``jax.experimental``.
    Returns a wrapper with the stable pre-0.8 keyword surface."""
    import inspect

    try:
        fn = jax.shard_map  # jax >= 0.8
    except AttributeError:
        from jax.experimental.shard_map import shard_map as fn

    params = inspect.signature(fn).parameters

    def wrapper(f, *, mesh, in_specs, out_specs, check_rep=False):
        kw = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
        if "check_rep" in params:
            kw["check_rep"] = check_rep
        elif "check_vma" in params:
            kw["check_vma"] = check_rep
        return fn(f, **kw)

    return wrapper
