"""Cluster-scale NLP training — the dl4j-spark-nlp analog (reference
``spark/dl4j-spark-nlp``: ``TextPipeline.java:1`` accumulator-built
vocab, ``spark/models/embeddings/word2vec/Word2Vec.java:1``
map-partitions training with accumulator-merged updates,
``glove/Glove.java`` + ``CoOccurrenceCalculator``).

TPU-native realization: where Spark shards sentences across executors
and merges per-partition vocab counters / parameter updates over the
shuffle network, here

- the **vocab build** shards the corpus into partitions counted
  independently and merged (the accumulator pattern, host-side), and
- the **training batch axis is sharded over the mesh 'data' axis**:
  the same fused skip-gram/CBOW/GloVe XLA steps run SPMD, with XLA
  inserting the gradient ``psum`` over ICI that Spark performed as an
  RDD aggregate. Updates are dense and synchronous, so the result is
  bitwise-equal to single-device training on the same batches — the
  equivalence Spark's parameter averaging only approximates.
"""

from __future__ import annotations

from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, List, Optional, Sequence

import jax
import numpy as np

from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.nlp.word2vec import SequenceVectors, Word2Vec
from deeplearning4j_tpu.parallel.mesh import (
    batch_sharding,
    build_mesh,
    replicated,
)


class TextPipeline:
    """Partitioned vocab construction (reference ``TextPipeline.java``:
    tokenize + per-partition word counts merged through Spark
    accumulators). Counting runs one task per partition and merges the
    partial Counters — the accumulator merge — so behavior matches the
    reference pipeline shape; on one host the tasks run on a thread
    pool (corpus IO dominates; the merge semantics are what carry to
    multi-host)."""

    def __init__(self, min_word_frequency: int = 1,
                 tokenizer_factory=None, n_partitions: int = 4):
        self.min_word_frequency = min_word_frequency
        self.tokenizer_factory = tokenizer_factory
        self.n_partitions = max(int(n_partitions), 1)

    def _tokens_of(self, sentence) -> List[str]:
        if isinstance(sentence, str):
            if self.tokenizer_factory is not None:
                return self.tokenizer_factory.create(
                    sentence
                ).get_tokens()
            return sentence.split()
        return list(sentence)

    def build_vocab(self, sentences: Iterable) -> VocabCache:
        corpus = [self._tokens_of(s) for s in sentences]
        parts = [
            corpus[i::self.n_partitions] for i in range(self.n_partitions)
        ]

        def count(part) -> Counter:
            c: Counter = Counter()
            for toks in part:
                c.update(toks)
            return c

        with ThreadPoolExecutor(max_workers=self.n_partitions) as ex:
            partials = list(ex.map(count, parts))
        merged: Counter = Counter()
        for c in partials:  # accumulator merge
            merged.update(c)
        cache = VocabCache()
        for word, n in sorted(
            merged.items(), key=lambda kv: (-kv[1], kv[0])
        ):
            if n >= self.min_word_frequency:
                cache.add(VocabWord(word, n))
        cache.total_word_count = sum(w.count for w in cache.words)
        return cache

    def to_id_sequences(self, sentences: Iterable,
                        cache: VocabCache) -> List[np.ndarray]:
        return [
            np.asarray(
                [cache.index_of(t) for t in self._tokens_of(s)
                 if t in cache],
                np.int32,
            )
            for s in sentences
        ]


class _MeshBatchMixin:
    """Shards the minibatch arrays over the mesh 'data' axis and keeps
    the embedding tables replicated; the inherited jitted steps then
    compile to SPMD programs with XLA-inserted gradient psum."""

    def _init_mesh(self, mesh) -> None:
        self.mesh = mesh if mesh is not None else build_mesh()
        self._batch_sharding = batch_sharding(self.mesh)
        self._rep = replicated(self.mesh)
        dp = self.mesh.shape["data"]
        if self.batch_size % dp:
            # round the pair-batch up so it splits over 'data'
            self.batch_size += dp - self.batch_size % dp

    def _shard_batch(self, a):
        return jax.device_put(np.asarray(a), self._batch_sharding)

    # the scan epoch is sharding-aware through _put_stacked, so the
    # bypassed _apply_batch override is fine here
    scan_path_compatible = True

    def _put_stacked(self, a):
        """[k, B, ...] scan-path arrays: shard the batch axis (axis 1)
        over 'data'."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(
            np.asarray(a), NamedSharding(self.mesh, P(None, "data"))
        )

    def _replicate_tables(self) -> None:
        lk = self.lookup
        lk.syn0 = jax.device_put(lk.syn0, self._rep)
        if lk.syn1 is not None:
            lk.syn1 = jax.device_put(lk.syn1, self._rep)
        if lk.syn1neg is not None:
            lk.syn1neg = jax.device_put(lk.syn1neg, self._rep)

    def _apply_batch(self, centers, contexts, mask, alpha, step):
        super()._apply_batch(
            self._shard_batch(centers), self._shard_batch(contexts),
            self._shard_batch(mask), alpha, step,
        )

    def _apply_cbow_batch(self, targets, ctx_ids, ctx_mask, mask, alpha,
                          step):
        super()._apply_cbow_batch(
            self._shard_batch(targets), self._shard_batch(ctx_ids),
            self._shard_batch(ctx_mask), self._shard_batch(mask),
            alpha, step,
        )


class ClusterWord2Vec(_MeshBatchMixin, Word2Vec):
    """Data-parallel Word2Vec over a device mesh (reference
    ``spark/models/embeddings/word2vec/Word2Vec.java`` — Spark's
    FirstIterationFunction/SecondIterationFunction become one SPMD
    program over the 'data' axis)."""

    def __init__(self, cache, sentences_ids, mesh=None, **kw):
        super().__init__(cache, sentences_ids, **kw)
        self._init_mesh(mesh)
        self._replicate_tables()


class ClusterSequenceVectors(_MeshBatchMixin, SequenceVectors):
    """Mesh-sharded generic SequenceVectors (DeepWalk-style callers)."""

    def __init__(self, cache, sequences: Sequence[np.ndarray],
                 mesh=None, **kw):
        super().__init__(cache, **kw)
        self._seqs = list(sequences)
        self._init_mesh(mesh)
        self._replicate_tables()

    def _sequences(self):
        return iter(self._seqs)


class ClusterGlove(Glove):
    """Data-parallel GloVe (reference ``spark/glove/Glove.java`` +
    ``CoOccurrenceCalculator`` — the co-occurrence count is the
    TextPipeline-partitioned host pass; the AdaGrad batch step runs
    SPMD over the 'data' axis)."""

    def __init__(self, cache, id_sequences, mesh=None, **kw):
        super().__init__(cache, id_sequences, **kw)
        self.mesh = mesh if mesh is not None else build_mesh()
        self._batch_sharding = batch_sharding(self.mesh)
        rep = replicated(self.mesh)
        dp = self.mesh.shape["data"]
        if self.batch_size % dp:
            self.batch_size += dp - self.batch_size % dp
        self._state = tuple(
            jax.device_put(s, rep) for s in self._state
        )

    def _put(self, a):
        """Shard the AdaGrad batch arrays over 'data' — the parent
        ``Glove.fit`` loop then compiles to the SPMD program."""
        return jax.device_put(np.asarray(a), self._batch_sharding)
