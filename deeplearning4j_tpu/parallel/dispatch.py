"""Bounded async step dispatch for the training hot loop.

JAX dispatch is asynchronous by construction — a jitted train step
returns device futures immediately — but the fit loops used to
serialize it right back with per-step host syncs: the divergence
guard's ``bool(ok)`` round-trips every step, and a listener reading
``score_value`` blocks until the step completes. The Julia-to-TPU
paper (PAPERS.md) identifies exactly these per-step host round-trips
as what keeps an XLA device from saturating.

:class:`AsyncDispatchWindow` is the fix, shared by
``DistributedTrainer.fit``, ``MultiLayerNetwork`` and
``ComputationGraph`` ``_fit_batches``:

- **bounded in-flight**: at most ``max_in_flight`` steps may be
  dispatched-but-incomplete; past that the window blocks on the
  OLDEST step's score (``jax.block_until_ready`` — a completion
  wait, not a value transfer), so host runahead cannot queue
  unbounded device work or pin unbounded batch memory.
- **lagged guard collection**: the guard's ok-flag for step *i* is
  read back at step *i + guard_lag* instead of immediately. This is
  safe because the in-jit ``select_updates`` already suppressed the
  bad update — the trajectory is bitwise identical whether the host
  learns about the bad step now or K steps later (tier-1-asserted).
  What shifts by up to K steps is host-side *policy*: skip counters
  and the ``max_consecutive`` abort. The ``rollback`` policy restores
  a checkpoint — state the next K steps would have consumed — so it
  forces ``guard_lag = 0`` (synchronous consult, exactly the
  pre-window behavior).
- **step-gap histogram**: ``training_step_gap_ms`` records the host
  wall-clock between consecutive dispatches — together with
  ``training_prefetch_wait_ms`` it answers "host-bound or
  device-bound?" from ``/metrics`` alone.

``drain()`` collects every outstanding flag and completion (epoch
boundaries, end of fit); ``abandon()`` drops them without consulting
the guard (exception unwind — never raise a guard abort while
another exception is in flight).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Optional

import time

from deeplearning4j_tpu.observability import profiler

GAP_MS_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                  1000.0)


class AsyncDispatchWindow:
    """One fit-loop's dispatch window. ``guard_fn`` returns the
    currently-installed DivergenceGuard (or None) so a listener
    flipping the guard mid-fit is honored; ``on_restore`` runs after
    a rollback (the distributed trainer re-places params on its
    mesh)."""

    def __init__(self, model=None,
                 guard_fn: Optional[Callable] = None,
                 on_restore: Optional[Callable] = None,
                 max_in_flight: int = 2,
                 guard_lag: Optional[int] = None,
                 registry=None):
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        if guard_lag is not None and guard_lag < 0:
            raise ValueError("guard_lag must be >= 0")
        self.model = model
        self.guard_fn = guard_fn or (lambda: None)
        self.on_restore = on_restore
        self.max_in_flight = int(max_in_flight)
        self.guard_lag = guard_lag
        self._flags: deque = deque()     # uncollected guard ok-flags
        self._inflight: deque = deque()  # unretired step scores
        self._last_dispatch: Optional[float] = None
        if registry is None:
            from deeplearning4j_tpu.observability.metrics import (
                default_registry,
            )

            registry = default_registry()
        self._gap_hist = registry.histogram(
            "training_step_gap_ms", buckets=GAP_MS_BUCKETS,
            help="host wall-clock between consecutive step "
                 "dispatches (ms)",
        )._default()

    # -- per-step -------------------------------------------------------

    def _effective_lag(self, guard) -> int:
        if guard is not None and getattr(guard, "policy", None) == \
                "rollback":
            # rollback restores checkpoint state the next steps would
            # consume: exactness requires the synchronous consult
            return 0
        if self.guard_lag is not None:
            return self.guard_lag
        return self.max_in_flight

    def push(self, score, ok=None) -> None:
        """Record one dispatched step: ``score`` (device scalar, used
        only as a completion handle) and the guard's ``ok`` flag
        (device bool, or None when no guard rode the step)."""
        now = time.perf_counter()
        if self._last_dispatch is not None:
            self._gap_hist.observe((now - self._last_dispatch) * 1e3)
        self._last_dispatch = now
        guard = self.guard_fn()
        if ok is not None and guard is not None:
            # remember WHICH step the flag belongs to: by consult time
            # the model's counter has moved on by up to lag steps, and
            # the guard's skipped-batch ledger must name the true one
            step = (int(self.model.iteration_count) - 1
                    if self.model is not None else -1)
            self._flags.append((step, ok))
            lag = self._effective_lag(guard)
            while len(self._flags) > lag:
                self._consult(self._flags.popleft(), guard)
        if score is not None:
            self._inflight.append(score)
            if len(self._inflight) > self.max_in_flight:
                # blocked here = device back-pressure: the window is
                # full and the host must wait for the oldest step —
                # the step profiler's dispatch_ms decomposition slot
                t0 = time.perf_counter()
                while len(self._inflight) > self.max_in_flight:
                    self._retire(self._inflight.popleft())
                prof = profiler.get_active_profiler()
                if prof is not None:
                    prof.note_dispatch_ms(
                        (time.perf_counter() - t0) * 1e3)

    # -- internals ------------------------------------------------------

    def _consult(self, flag, guard) -> None:
        step, ok = flag
        if bool(ok):  # the (amortized) device sync
            guard.good_step()
        else:
            guard.bad_step(self.model, on_restore=self.on_restore,
                           step_index=step)

    @staticmethod
    def _retire(score) -> None:
        import jax

        jax.block_until_ready(score)

    # -- lifecycle ------------------------------------------------------

    @property
    def pending(self) -> int:
        """Uncollected guard flags + unretired steps (introspection)."""
        return len(self._flags) + len(self._inflight)

    def drain(self) -> None:
        """Collect every outstanding guard flag and block until all
        in-flight steps complete. May raise ``DL4JFaultException``
        (the guard's max_consecutive abort, surfaced at the epoch
        boundary instead of mid-window)."""
        guard = self.guard_fn()
        while self._flags:
            flag = self._flags.popleft()
            if guard is not None:
                self._consult(flag, guard)
        t0 = time.perf_counter()
        had = bool(self._inflight)
        while self._inflight:
            self._retire(self._inflight.popleft())
        if had:
            prof = profiler.get_active_profiler()
            if prof is not None:
                # drain happens at epoch/fit boundaries: the wait is
                # device completion time, attributed to the last step
                prof.note_device_ms((time.perf_counter() - t0) * 1e3)

    def abandon(self) -> None:
        """Drop outstanding work without consulting the guard — the
        exception-unwind path."""
        self._flags.clear()
        self._inflight.clear()
