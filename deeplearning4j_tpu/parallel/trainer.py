"""Distributed trainer — the idiomatic replacement for
``SparkDl4jMultiLayer``/``ParameterAveragingTrainingMaster``
(reference SURVEY.md §3.2): instead of broadcast -> N local fits ->
RDD aggregate -> divide, the train step is jitted over a Mesh with the
batch sharded on the ``data`` axis and params replicated (or sharded
over ``model`` for tensor parallelism). XLA GSPMD inserts the gradient
all-reduce (psum over ICI) where Spark shuffles parameters over the
network — per-STEP synchronization at interconnect speed rather than
per-averaging-round at shuffle speed.

Two modes, matching the reference's semantics split:
- ``DistributedTrainer``: per-step gradient all-reduce (do-it-right
  mode; what the reference would be with synchronous SGD).
- ``ParallelWrapper`` (in ``wrapper.py``): periodic parameter
  averaging faithfully reproducing ParallelWrapper /
  ParameterAveragingTrainingMaster trajectories for equivalence tests.
"""

from __future__ import annotations

import time
import warnings
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn import core
from deeplearning4j_tpu.observability import profiler
from deeplearning4j_tpu.observability.trace import get_tracer
from deeplearning4j_tpu.parallel.mesh import build_mesh


def _default_registry():
    from deeplearning4j_tpu.observability.metrics import default_registry

    return default_registry()


def _fused_pmean(tree, axis_name: str):
    """pmean every leaf of ``tree`` through ONE all-reduce: ravel the
    leaves into a single flat f32 vector, reduce once, unflatten.

    The gradient-bucketing trick every DDP framework applies before
    NCCL, for the same reason it applies on TPU: a ResNet-50 gradient
    tree + BN-state tree is ~260 leaves, and 260 small all-reduces pay
    260 collective launches/rendezvous where one fused reduction pays
    one. Measured on the 8-device host mesh: the per-leaf form cost
    ~20% of the whole train step in rendezvous overhead that the
    separately-timed pieces (compute / reduction / update) do not
    show. XLA's all-reduce combiner does this in some pipelines, but
    not across the pattern the shard_map step emits.

    Only floating-point leaves ride the flat bucket (ravel_pytree
    promotes to a common dtype — averaging an int step counter or bool
    flag through f32 would silently truncate); non-inexact leaves
    (step counters, flags — identical across replicas by construction,
    like the reference's per-worker iteration counts) pass through
    unchanged rather than being float-averaged.
    """
    from jax.flatten_util import ravel_pytree

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    inexact = [jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)
               for l in leaves]
    if not any(inexact):
        return tree  # nothing to average; skip the collective
    if all(inexact) and len(leaves) <= 1:
        return jax.lax.pmean(tree, axis_name)
    flat, unravel = ravel_pytree(
        [l for l, fl in zip(leaves, inexact) if fl]
    )
    fused = iter(unravel(jax.lax.pmean(flat, axis_name)))
    out = [next(fused) if fl else l
           for l, fl in zip(leaves, inexact)]
    return jax.tree_util.tree_unflatten(treedef, out)


def default_partition_rules(layer, param_name: str, shape) -> P:
    """Tensor-parallel sharding rules per param (net-new vs the
    reference, which has no TP). Column-parallel dense/conv weights on
    the 'model' axis; replicate small/1-d params.

    Shapes follow our param conventions: dense W [in, out], conv W
    [out, in, kh, kw], LSTM W [in, 4n] / RW [n, 4n], embedding W
    [vocab, dim]."""
    from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer
    from deeplearning4j_tpu.nn.layers.feedforward import EmbeddingLayer

    if len(shape) >= 2:
        if isinstance(layer, ConvolutionLayer) and param_name == "W":
            return P("model", None, None, None)
        if isinstance(layer, EmbeddingLayer) and param_name == "W":
            return P("model", None)  # vocab-sharded
        if param_name in ("W", "RW", "WF", "WB", "RWF", "RWB"):
            return P(None, "model")  # column parallel
    return P()  # replicate biases / small vectors


def _row_sharded_embedding_param(layer, param_name: str) -> bool:
    """The ``embeddings/`` sharding shape inside the engines: a
    ``SparseEmbeddingLayer``'s table rows partition over the DATA axis
    (independent of tensor_parallel — this is capacity sharding, not
    TP), so the table, and under GSPMD its gradient and updater rows,
    scale with mesh width."""
    from deeplearning4j_tpu.nn.layers.feedforward import (
        SparseEmbeddingLayer,
    )

    return (
        isinstance(layer, SparseEmbeddingLayer)
        and getattr(layer, "row_sharded", False)
        and param_name == "W"
    )


class DistributedTrainer:
    """Data (+ optional tensor) parallel trainer for a
    MultiLayerNetwork or ComputationGraph.

    The model's own jitted step is re-jitted with explicit shardings:
    params/updater-state/layer-state per the partition rules, batch on
    'data'. Single-chip and multi-chip use the same code path (a 1x1
    mesh degenerates to the plain step)."""

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 tensor_parallel: bool = False,
                 partition_rules=default_partition_rules,
                 batch_stats: str = "auto",
                 divergence_guard=None,
                 max_in_flight: int = 2,
                 guard_lag: Optional[int] = None,
                 zero: bool = False):
        """``batch_stats`` picks the data-parallel batch-statistics
        semantics:

        - ``"sync"``: batch-coupled layers (BatchNormalization) see
          the GLOBAL batch — training is bitwise-equivalent to
          single-device (GSPMD step; one all-reduce per BN layer on
          the critical path).
        - ``"local"``: every replica computes batch stats on its own
          shard — the reference's worker semantics (Spark workers /
          ParallelWrapper replicas never cross-synced BN,
          ``ParameterAveragingTrainingMaster.java:74``); running
          stats are averaged across replicas like the reference
          averages state. One gradient pmean per step, no per-BN
          rendezvous.
        - ``"auto"`` (default): the shard_map step whenever it is
          EXACTLY equivalent to sync — no batch-coupled layer, no
          dropout (replicas would draw independent masks), and the
          minibatch carries no loss masks (per-shard mask counts
          would reweight the mean) — else the GSPMD step. The default
          never changes the training trajectory vs single-device.

        ``zero=True`` (ZeRO-1): optimizer state (Adam/RMSProp moments
        etc.) is stored in the flattened-leaf layout sharded
        ``P("data")`` — each device holds ~1/N of every moment instead
        of a full replica, so the largest trainable model grows with
        the mesh. After the gradient all-reduce each device updates
        only its slice and GSPMD all-gathers the updated param slices
        back. The per-element update math is unchanged: the trajectory
        is bitwise identical to the replicated baseline
        (``updater_state_bytes_per_device`` / ``zero_shard_bytes``
        gauge the memory win).
        """
        if batch_stats not in ("auto", "sync", "local"):
            raise ValueError(
                f"batch_stats must be auto|sync|local, got {batch_stats!r}"
            )
        if batch_stats == "local" and tensor_parallel:
            raise ValueError(
                "batch_stats='local' is incompatible with "
                "tensor_parallel=True: sharded weights need the GSPMD "
                "step, which computes global (sync) batch statistics"
            )
        if zero and tensor_parallel:
            raise ValueError(
                "zero=True shards optimizer state over the data axis; "
                "tensor_parallel=True already shards it with the "
                "params — combining the two layouts is not supported"
            )
        if zero and batch_stats == "local":
            raise ValueError(
                "zero=True needs the GSPMD step; batch_stats='local' "
                "forces the shard_map step, whose per-device replicated "
                "updater state is exactly what zero removes"
            )
        self.zero = bool(zero)
        registry = _default_registry()
        self._m_upd_bytes = registry.gauge(
            "updater_state_bytes_per_device",
            help="optimizer-state bytes resident on ONE device "
                 "(replicated leaves count full size; zero shards "
                 "count ~1/N)",
        )._default()
        self._m_zero_shard_bytes = registry.gauge(
            "zero_shard_bytes",
            help="bytes of this device's 1/N flattened optimizer-state "
                 "shard under zero=True (0 when zero is off)",
        )._default()
        self.model = model
        self.mesh = mesh if mesh is not None else build_mesh()
        self.tensor_parallel = tensor_parallel
        self.partition_rules = partition_rules
        self.batch_stats = batch_stats
        # resilience.DivergenceGuard: when set, the jitted steps test
        # loss + gradient global-norm for finiteness and suppress the
        # update on a bad step (select in-jit); host-side policy then
        # skips or rolls back to the last checkpoint. Reading the
        # ok-flag synchronizes per step.
        self.divergence_guard = divergence_guard
        # back-reference for checkpoint capture: guard_state_doc reads
        # it when the model carries no guard of its own
        model._ckpt_guard = divergence_guard
        # async dispatch (fit loop only; fit_minibatch called directly
        # keeps the synchronous per-step consult): at most
        # max_in_flight steps dispatched-but-incomplete, guard flags
        # collected guard_lag steps late (None -> max_in_flight;
        # rollback policy forces 0 — see parallel/dispatch.py)
        self.max_in_flight = max(int(max_in_flight), 1)
        self.guard_lag = guard_lag
        self._epoch_span = None  # live train.epoch span during fit
        self._is_graph = hasattr(model.conf, "vertices")
        if model.params is None:
            model.init()
        self._param_shardings = self._make_param_shardings()
        self._place_params()
        self._jit_step_sm = None
        self._jit_step_gspmd = None
        self._jit_megastep_dist = None
        # step-telemetry / loss-scale / grad-accum flags the jitted
        # steps were built against (they live on the MODEL so the same
        # hooks cover both engines); a change rebuilds the steps
        self._built_telemetry = self._telemetry_enabled()
        self._built_ls = core.loss_scale_active(model)
        self._built_accum = int(getattr(model, "grad_accum", 1))
        self._built_sg = self._sg_config() is not None

    def _telemetry_enabled(self) -> bool:
        return bool(getattr(self.model, "_telemetry_grad_norm", False))

    def _sg_config(self):
        """StatGuardConfig of the TRAINER's guard (the trainer and
        engine guards are separate installs by design)."""
        guard = self.divergence_guard
        return getattr(guard, "stats", None) if guard is not None else None

    def enable_step_telemetry(self, enabled: bool = True) -> None:
        """(Un)install step telemetry on the distributed steps: like
        ``MultiLayerNetwork.enable_step_telemetry``, the jitted step
        additionally returns the gradient global L2 norm (computed
        post-pmean, so it is the GLOBAL gradient's norm — identical
        on every replica). Works for either engine under the trainer;
        the flag is stored on the model so
        ``observability.TelemetryListener`` finds it there."""
        self.model._telemetry_grad_norm = enabled

    def _layer_confs(self):
        conf = self.model.conf
        if self._is_graph:
            return [
                v.layer_conf for v in conf.vertices.values()
                if getattr(v, "layer_conf", None) is not None
            ]
        return list(conf.layers)

    def _uses_batch_statistics(self) -> bool:
        return any(
            layer.uses_batch_statistics()
            for layer in self._layer_confs()
        )

    def _uses_dropout(self) -> bool:
        return any(
            getattr(layer, "dropout", 0.0) > 0.0
            for layer in self._layer_confs()
        )

    def _pick_shard_map(self, has_masks: bool) -> bool:
        if self.tensor_parallel:
            return False
        if core.has_row_sharded_embedding(self.model):
            # the shard_map step replicates every param per device —
            # the opposite of a row-sharded table; GSPMD places the
            # P("data", None) W and shards its gradient to match
            return False
        if self.zero:
            # the flattened P("data") updater layout is a GSPMD
            # sharding; the shard_map step would replicate it again
            return False
        if (
            core.loss_scale_active(self.model)
            or int(getattr(self.model, "grad_accum", 1)) > 1
            or self._sg_config() is not None
        ):
            # loss-scale / stat-guard state and microbatch scans ride
            # the GSPMD step
            return False
        if self.batch_stats == "local":
            return True
        if self.batch_stats == "sync":
            return False
        return (
            not self._uses_batch_statistics()
            and not self._uses_dropout()
            and not has_masks
        )

    # -- sharding layout ------------------------------------------------

    def _layer_of(self, name: str):
        m = self.model
        if hasattr(m, "conf") and hasattr(m.conf, "vertices"):
            v = m.conf.vertices[name]
            return v.layer_conf
        idx = m.layer_names.index(name)
        return m.conf.layers[idx]

    def _spec_for(self, lname: str, pname: str, arr) -> P:
        layer = self._layer_of(lname)
        if _row_sharded_embedding_param(layer, pname):
            # Eligibility fallbacks, loud not silent:
            # - zero=True: the flattened P("data") moment layout and
            #   the row-sharded param layout can't both own the data
            #   axis for this leaf — keep W replicated under zero.
            # - vocab not divisible by the data axis: replicate
            #   (ShardedEmbeddingTable pads; engine params don't).
            if self.zero:
                warnings.warn(
                    f"SparseEmbeddingLayer {lname!r}: row sharding "
                    "falls back to replication under zero=True (the "
                    "flat P('data') updater layout owns the data "
                    "axis); use the embeddings/ subsystem for tables "
                    "that need both", stacklevel=3,
                )
                return P()
            if arr.shape[0] % self.mesh.shape["data"] == 0:
                return P("data", None)
            warnings.warn(
                f"SparseEmbeddingLayer {lname!r}: vocab "
                f"{arr.shape[0]} not divisible by data axis "
                f"{self.mesh.shape['data']}; falling back to "
                "replication", stacklevel=3,
            )
            return P()
        if not self.tensor_parallel:
            return P()
        spec = self.partition_rules(
            layer, pname, arr.shape
        )
        # Fall back to replication when a sharded dim isn't divisible
        # by its mesh axis (e.g. a 3-class output head on model=4).
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            if arr.shape[dim] % self.mesh.shape[axis] != 0:
                return P()
        return spec

    def _make_param_shardings(self):
        mesh = self.mesh
        return {
            ln: {
                pn: NamedSharding(mesh, self._spec_for(ln, pn, arr))
                for pn, arr in lp.items()
            }
            for ln, lp in self.model.params.items()
        }

    def _place_params(self) -> None:
        """Move params/updater-state onto the mesh with their target
        shardings (the reference's broadcast step, done once). With
        ``zero=True`` the updater state is flattened, zero-padded to a
        multiple of the data-parallel degree, and sharded
        ``P("data")`` instead of replicated — ~1/N of every moment per
        device. An incoming zero layout (checkpoint rollback,
        survivor-mesh recovery from a DIFFERENT mesh width) is first
        gathered back to canonical shapes, so re-sharding 8-wide state
        onto 4 devices — or onto 1, the replicated fallback — is the
        same code path."""
        m = self.model
        if getattr(m, "_zero_layout", None):
            # canonicalize first: the live layout may belong to a
            # previous mesh (elastic recovery / cross-mesh resume)
            m.updater_state = core.zero_gather_updater_state(
                m.updater_state, m.params
            )
            m._zero_layout = None
        m.params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, s), m.params,
            self._param_shardings,
        )
        rep = NamedSharding(self.mesh, P())
        if self.zero:
            n_data = int(self.mesh.shape["data"])
            flat = NamedSharding(self.mesh, P("data"))

            def shard_leaf(a):
                h = np.asarray(a)
                v = h.reshape(-1)
                pad = core.zero_flat_size(h.shape, n_data) - v.size
                if pad:
                    v = np.concatenate([v, np.zeros(pad, h.dtype)])
                return jax.device_put(v, flat)

            m.updater_state = {
                ln: {
                    pn: tuple(shard_leaf(a) for a in tup)
                    for pn, tup in lp.items()
                }
                for ln, lp in m.updater_state.items()
            }
            m._zero_layout = {"shards": n_data}
        else:
            m.updater_state = {
                ln: {
                    pn: tuple(
                        jax.device_put(
                            a, self._param_shardings[ln][pn]
                        )
                        for a in tup
                    )
                    for pn, tup in lp.items()
                }
                for ln, lp in m.updater_state.items()
            }
        m.state = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, rep), m.state
        )
        # the layout is baked into every compiled step: the engine's
        # own cached steps must not be fed state in the other layout
        m._jit_step = None
        m._jit_multi_step = None
        m._jit_megastep = None
        self._jit_megastep_dist = None
        self._publish_updater_gauges()

    def _publish_updater_gauges(self) -> None:
        """Per-device updater-state residency, measured from the live
        arrays' addressable shards (what acceptance asserts: zero's
        per-device bytes ~1/N of replicated)."""
        per_dev = 0
        shard_bytes = 0
        for leaf in jax.tree_util.tree_leaves(self.model.updater_state):
            if not isinstance(leaf, jax.Array):
                per_dev += int(np.asarray(leaf).nbytes)
                continue
            shards = leaf.addressable_shards
            if not shards:
                continue
            nb = int(shards[0].data.nbytes)
            per_dev += nb
            if self.zero:
                shard_bytes += nb
        self._m_upd_bytes.set(float(per_dev))
        self._m_zero_shard_bytes.set(float(shard_bytes))
        self._publish_embedding_gauge()

    def _publish_embedding_gauge(self) -> None:
        """Per-device residency of row-sharded embedding tables (the
        ``embedding_shard_bytes`` the embeddings/ subsystem also
        publishes): bytes of ONE device's shard of every
        SparseEmbeddingLayer ``W``, summed."""
        if not core.has_row_sharded_embedding(self.model):
            return
        total = 0
        for lname, lp in self.model.params.items():
            if not _row_sharded_embedding_param(
                self._layer_of(lname), "W"
            ) or "W" not in lp:
                continue
            w = lp["W"]
            shards = getattr(w, "addressable_shards", None)
            if shards:
                total += int(shards[0].data.nbytes)
        from deeplearning4j_tpu.embeddings.table import note_shard_bytes

        note_shard_bytes(total)

    # -- step -----------------------------------------------------------

    def _step_for(self, has_masks: bool):
        """Lazily-built step per flavor; the choice is per-minibatch
        (``auto`` must see whether THIS batch carries masks)."""
        ls_now = core.loss_scale_active(self.model)
        accum_now = int(getattr(self.model, "grad_accum", 1))
        sg_now = self._sg_config() is not None
        if (
            self._telemetry_enabled() != self._built_telemetry
            or ls_now != self._built_ls
            or accum_now != self._built_accum
            or sg_now != self._built_sg
        ):
            # a baked-in knob flipped since the steps were built (e.g.
            # a TelemetryListener attached mid-run, fit(grad_accum=K)
            # changed the microbatch count): rebuild both
            self._built_telemetry = self._telemetry_enabled()
            self._built_ls = ls_now
            self._built_accum = accum_now
            self._built_sg = sg_now
            self._jit_step_sm = None
            self._jit_step_gspmd = None
            self._jit_megastep_dist = None
        if self._pick_shard_map(has_masks):
            if self._jit_step_sm is None:
                self._jit_step_sm = self._build_shard_map_step()
            return self._jit_step_sm
        if self._jit_step_gspmd is None:
            self._jit_step_gspmd = self._build_gspmd_step()
        return self._jit_step_gspmd

    def _build_shard_map_step(self):
        """Data-parallel train step as an explicit per-device program
        (``shard_map``): every device computes loss/grads on ITS batch
        shard with LOCAL batch statistics (BatchNormalization sees the
        per-replica batch — exactly the reference's semantics: Spark
        workers / ParallelWrapper replicas never cross-synced BN,
        ``ParameterAveragingTrainingMaster.java:74``), then gradients
        meet in a single ``pmean``. Under GSPMD the same model emits a
        latency-bound all-reduce per BN layer ON the critical path —
        measured ~9% of a ResNet-50 step on an 8-device mesh; here the
        only rendezvous is the end-of-step gradient reduction.

        Layer state (BN running stats) is pmean'd after the update so
        replicas stay bit-identical — the reference averages updater
        state and parameters across workers the same way. Dropout keys
        fold in the device index (reference workers draw independent
        RNG streams)."""
        from deeplearning4j_tpu.parallel.compat import shard_map_compat

        shard_map = shard_map_compat()

        guarded = self.divergence_guard is not None
        telemetry = self._telemetry_enabled()
        m = self.model
        mesh = self.mesh
        updater = m.updater_def
        is_graph = self._is_graph
        # recurrent carry is per-minibatch scratch (the engines reset
        # it after every fit_minibatch): restore the incoming entries
        # instead of pmean'ing batch-sized h/c across replicas — the
        # same trick MultiLayerNetwork._build_multi_step uses
        if is_graph:
            recurrent_names = [
                n for n in m.layer_vertex_names
                if m.conf.vertices[n].layer_conf.is_recurrent()
            ]
        else:
            recurrent_names = [
                n for n, layer in zip(m.layer_names, m.conf.layers)
                if layer.is_recurrent()
            ]

        def step(params, upd_state, state, x, labels, mask, fmask, lrs,
                 t, rng):
            rng = jax.random.fold_in(
                rng, jax.lax.axis_index("data")
            )

            def loss_fn(p):
                if is_graph:
                    s, new_state = m._score_pure(
                        p, state, x, labels, mask, rng, train=True,
                        fmasks=fmask,
                    )
                else:
                    s, new_state = m._score_pure(
                        p, state, x, labels, mask, rng, train=True,
                        fmask=fmask,
                    )
                return s, new_state

            (score, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            new_state = dict(new_state)
            for name in recurrent_names:
                if name in new_state:
                    new_state[name] = state[name]
            # ONE fused all-reduce for gradients + score + layer state
            # (BN running stats averaged across replicas like the
            # reference averages state) — see _fused_pmean
            grads, score, new_state = _fused_pmean(
                (grads, score, new_state), "data"
            )
            # post-pmean the grads/score are replica-identical, so the
            # shared finish (updater + telemetry norm + guard select —
            # nn/core.py) computes the same trees on every replica;
            # the telemetry norm is the GLOBAL gradient's L2 norm
            return core.finish_step(
                updater, grads, score, new_state, params, upd_state,
                state, lrs, t, guarded=guarded, telemetry=telemetry,
            )

        rep = P()
        dp = P("data")
        n_out = 4 + int(telemetry) + int(guarded)
        sharded = shard_map(
            step, mesh=mesh,
            in_specs=(rep, rep, rep, dp, dp, dp, dp, rep, rep, rep),
            out_specs=tuple(rep for _ in range(n_out)),
            check_rep=False,
        )
        return jax.jit(sharded, donate_argnums=(0, 1, 2))

    def _build_gspmd_step(self):
        guarded = self.divergence_guard is not None
        telemetry = self._telemetry_enabled()
        ls_active = self._built_ls
        grad_accum = self._built_accum
        sg_cfg = self._sg_config()
        sg_active = sg_cfg is not None
        m = self.model
        mesh = self.mesh
        rep = NamedSharding(mesh, P())
        batch = NamedSharding(mesh, P("data"))
        if self.zero:
            # ZeRO layout: every updater leaf is a flat padded vector
            # sharded over 'data' — each device applies the update to
            # its 1/N slice; the replicated out_sharding on params
            # makes GSPMD insert the all-gather of the updated slices
            n_data = int(mesh.shape["data"])
            flat = NamedSharding(mesh, P("data"))
            upd_shardings = {
                ln: {
                    pn: tuple(flat for _ in range(len(tup)))
                    for pn, tup in lp.items()
                }
                for ln, lp in m.updater_state.items()
            }

            def flatten(a):
                # the inner replicated pin stops the flat sharding
                # from propagating BACKWARD into the grad computation
                # (under grad-accum it would re-partition the scan
                # body's matmuls and change reduction order — breaking
                # the bitwise-vs-replicated trajectory)
                a = jax.lax.with_sharding_constraint(a, rep)
                return jax.lax.with_sharding_constraint(
                    core.zero_flatten_leaf(a, n_data), flat
                )

            unflatten = core.zero_unflatten_leaf
        else:
            # updater-state sharding mirrors params
            upd_shardings = {
                ln: {
                    pn: tuple(
                        self._param_shardings[ln][pn]
                        for _ in range(len(tup))
                    )
                    for pn, tup in lp.items()
                }
                for ln, lp in m.updater_state.items()
            }
            flatten = unflatten = None
        # Layer state uses a prefix sharding (one NamedSharding for the
        # whole subtree): its pytree structure changes when recurrent
        # carry (h, c) appears in the step output.
        state_shardings = rep
        updater = m.updater_def
        is_graph = self._is_graph
        recurrent_names = (
            m._recurrent_names() if hasattr(m, "_recurrent_names")
            else ()
        )

        def score_fn(p, state, x, labels, mask, fmask, rng):
            if is_graph:
                # ComputationGraph takes lists + per-output masks
                return m._score_pure(
                    p, state, x, labels, mask, rng, train=True,
                    fmasks=fmask,
                )
            return m._score_pure(
                p, state, x, labels, mask, rng, train=True,
                fmask=fmask,
            )

        def step(params, upd_state, state, x, labels, mask, fmask, lrs,
                 t, rng, *ls_args):
            ls = ls_args[0] if ls_active else None
            sg = ls_args[1 if ls_active else 0] if sg_active else None
            scale = ls["scale"] if ls_active else None
            if grad_accum > 1:
                (score, new_state), grads = core.accum_grad_step(
                    score_fn, params, state, x, labels, mask, fmask,
                    rng, grad_accum, scale=scale,
                    recurrent_names=recurrent_names,
                )
            else:
                (score, new_state), grads = core.grad_step(
                    score_fn, params, state, x, labels, mask, fmask,
                    rng, scale=scale,
                )
            return core.finish_step(
                updater, grads, score, new_state, params, upd_state,
                state, lrs, t, guarded=guarded, telemetry=telemetry,
                ls=ls, flatten=flatten, unflatten=unflatten,
                sg=sg, sg_cfg=sg_cfg,
            )

        out_shardings = (
            self._param_shardings, upd_shardings, state_shardings, rep,
        )
        if telemetry:
            out_shardings = out_shardings + (rep,)
        if ls_active:
            out_shardings = out_shardings + (rep,)
        if sg_active:
            out_shardings = out_shardings + (rep,)
        if guarded:
            out_shardings = out_shardings + (rep,)
        in_shardings = (
            self._param_shardings, upd_shardings, state_shardings,
            batch, batch, batch, batch, None, None, None,
        )
        if ls_active:
            in_shardings = in_shardings + (None,)
        if sg_active:
            in_shardings = in_shardings + (None,)
        return jax.jit(
            step,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=(0, 1, 2),
        )

    # -- megastep (K fused steps / dispatch) ----------------------------

    def _can_megastep(self) -> bool:
        """Megastep eligibility under this trainer: the model-side
        checks (core.can_megastep) plus this trainer's OWN guard —
        trainer and engine guards are separate installs, and a
        ROLLBACK-policy guard needs the per-step program."""
        from deeplearning4j_tpu.resilience.guard import ROLLBACK

        g = self.divergence_guard
        if g is not None and g.policy == ROLLBACK:
            return False
        return core.can_megastep(self.model)

    def _megastep_for(self):
        """Lazily-built fused K-step executable (knob changes rebuild
        it — same discipline as ``_step_for``; K itself is NOT baked
        in, the scanned program just retraces on a new chunk shape)."""
        ls_now = core.loss_scale_active(self.model)
        accum_now = int(getattr(self.model, "grad_accum", 1))
        sg_now = self._sg_config() is not None
        if (
            self._telemetry_enabled() != self._built_telemetry
            or ls_now != self._built_ls
            or accum_now != self._built_accum
            or sg_now != self._built_sg
        ):
            self._built_telemetry = self._telemetry_enabled()
            self._built_ls = ls_now
            self._built_accum = accum_now
            self._built_sg = sg_now
            self._jit_step_sm = None
            self._jit_step_gspmd = None
            self._jit_megastep_dist = None
        if self._jit_megastep_dist is None:
            self._jit_megastep_dist = self._build_gspmd_megastep()
        return self._jit_megastep_dist

    def _build_gspmd_megastep(self):
        """The GSPMD flavor of ``core.build_megastep``: the same
        scanned K-step body, jitted here with explicit shardings —
        stacked batch blocks ride ``P(None, "data")`` (each step's
        [b, ...] slice scattered over the data axis, exactly the
        per-step layout), zero's flat updater moments stay ``P("data")``
        INSIDE the scanned body, and params/state donate."""
        ls_active = self._built_ls
        sg_cfg = self._sg_config()
        m = self.model
        mesh = self.mesh
        rep = NamedSharding(mesh, P())
        chunk = NamedSharding(mesh, P(None, "data"))
        if self.zero:
            n_data = int(mesh.shape["data"])
            flat = NamedSharding(mesh, P("data"))
            upd_shardings = {
                ln: {
                    pn: tuple(flat for _ in range(len(tup)))
                    for pn, tup in lp.items()
                }
                for ln, lp in m.updater_state.items()
            }

            def flatten(a):
                # same double pin as _build_gspmd_step: stop the flat
                # sharding from propagating backward into the grads
                a = jax.lax.with_sharding_constraint(a, rep)
                return jax.lax.with_sharding_constraint(
                    core.zero_flatten_leaf(a, n_data), flat
                )

            unflatten = core.zero_unflatten_leaf
        else:
            upd_shardings = {
                ln: {
                    pn: tuple(
                        self._param_shardings[ln][pn]
                        for _ in range(len(tup))
                    )
                    for pn, tup in lp.items()
                }
                for ln, lp in m.updater_state.items()
            }
            flatten = unflatten = None
        is_graph = self._is_graph

        def score_fn(p, state, x, labels, mask, fmask, rng):
            if is_graph:
                return m._score_pure(
                    p, state, x, labels, mask, rng, train=True,
                    fmasks=fmask,
                )
            return m._score_pure(
                p, state, x, labels, mask, rng, train=True,
                fmask=fmask,
            )

        mega = core.build_megastep(
            score_fn, m.updater_def, cast=None,
            recurrent_names=(
                m._recurrent_names()
                if hasattr(m, "_recurrent_names") else ()
            ),
            guarded=self.divergence_guard is not None,
            telemetry=self._built_telemetry,
            loss_scale=ls_active, stat_guard=sg_cfg,
            grad_accum=self._built_accum,
            flatten=flatten, unflatten=unflatten, jit=False,
        )
        in_shardings = (
            self._param_shardings, upd_shardings, rep,
            chunk, chunk, chunk, chunk, None, None, None,
        )
        # out: (params, upd, state, metrics, it0+k) [+ls] [+sg]
        out_shardings = (
            self._param_shardings, upd_shardings, rep, rep, rep,
        )
        if ls_active:
            in_shardings = in_shardings + (None,)
            out_shardings = out_shardings + (rep,)
        if sg_cfg is not None:
            in_shardings = in_shardings + (None,)
            out_shardings = out_shardings + (rep,)
        return jax.jit(
            mega,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=(0, 1, 2),
        )

    # -- input placement ------------------------------------------------

    def _pad_rows(self, a, pad: int):
        """Pad ``pad`` zero rows onto axis 0 (host-side; runs before
        placement so the padded batch transfers as one array)."""
        a = np.asarray(a)
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths)

    def _pad_minibatch(self, ds, batch_n: int, n_data: int):
        """Pad-and-mask a trailing partial batch up to the next
        multiple of the data-parallel degree (the training analog of
        serving's ``output_padded`` masking trick): features/labels
        gain zero rows, and a labels mask zeroes the padding out of
        the loss — ``losses.score`` divides by the mask sum, so score
        and gradients equal the unpadded batch's exactly, and the
        epoch-end remnant trains instead of raising.

        Batch-coupled layers are the one exception: padding rows
        would enter BatchNormalization's batch statistics, so those
        configs keep the explicit error."""
        from deeplearning4j_tpu.datasets.api import (
            DataSet, MultiDataSet,
        )

        if self._uses_batch_statistics():
            raise ValueError(
                f"Batch size {batch_n} is not divisible by the data-"
                f"parallel degree {n_data}, and this model uses batch "
                "statistics (BatchNormalization) — zero padding rows "
                "would corrupt the batch stats. Drop or regroup the "
                "trailing partial batch."
            )
        pad = n_data - batch_n % n_data

        def mask_ones(labels):
            y = np.asarray(labels)
            # per-row loss mask: [b] for 2-d labels, [b, t] for
            # sequence labels (matches losses._to_row_mask)
            if y.ndim == 3:
                return np.ones((y.shape[0], y.shape[2]), np.float32)
            return np.ones((y.shape[0],), np.float32)

        def padded(v, make_mask_from=None):
            if v is None:
                if make_mask_from is None:
                    return None
                v = mask_ones(make_mask_from)
            return self._pad_rows(v, pad)

        if self._is_graph:
            def aslist(v):
                if v is None:
                    return None
                return list(v) if isinstance(v, (list, tuple)) else [v]

            feats = aslist(ds.features)
            labels = aslist(ds.labels)
            lmasks = aslist(getattr(ds, "labels_masks", None)
                            or getattr(ds, "labels_mask", None))
            fmasks = aslist(getattr(ds, "features_masks", None)
                            or getattr(ds, "features_mask", None))
            lmasks = lmasks or [None] * len(labels)
            fmasks = fmasks or [None] * len(feats)
            return MultiDataSet(
                features=[padded(f) for f in feats],
                labels=[padded(y) for y in labels],
                # every output slot gets a mask so each padded row is
                # excluded from each output's loss term
                labels_masks=[
                    padded(m, make_mask_from=y)
                    for m, y in zip(lmasks, labels)
                ],
                features_masks=(
                    None
                    if all(m is None for m in fmasks)
                    else [padded(m) for m in fmasks]
                ),
            )
        return DataSet(
            features=padded(ds.features),
            labels=padded(ds.labels),
            labels_mask=padded(
                getattr(ds, "labels_mask", None),
                make_mask_from=ds.labels,
            ),
            features_mask=padded(getattr(ds, "features_mask", None)),
        )

    def place_minibatch(self, ds):
        """Materialize, pad-and-mask (trailing partial batches), cast,
        and scatter one minibatch onto the mesh with the ``data``
        sharding. This is the host work ``fit_minibatch`` used to do
        inline; ``PrefetchIterator(base, placement=trainer.
        place_minibatch)`` runs it on the prefetch thread instead, so
        the step dispatch never waits on a host->device copy.
        Idempotent: an already-placed batch passes through."""
        from deeplearning4j_tpu.datasets.api import PlacedDataSet

        if isinstance(ds, PlacedDataSet):
            return ds
        m = self.model
        dtype = jnp.dtype(m.conf.dtype)
        # Place batch arrays WITH the data sharding (the scatter
        # happens during the host->device copy); jnp.asarray would
        # land them on device 0 and leave GSPMD a full reshard before
        # every step — measurable overhead at dp degree 8.
        batch_sharding = NamedSharding(self.mesh, P("data"))
        n_data = self.mesh.shape["data"]
        first = ds.features
        if isinstance(first, (list, tuple)):
            first = first[0]
        batch_n = int(np.shape(first)[0])
        k_accum = int(getattr(m, "grad_accum", 1))
        if k_accum > 1 and batch_n % (k_accum * n_data) != 0:
            raise ValueError(
                f"grad_accum={k_accum} on a {n_data}-wide data mesh "
                f"needs the batch to split into {k_accum} microbatches "
                f"of whole shards; got batch size {batch_n} (make it a "
                f"multiple of {k_accum * n_data})"
            )
        if batch_n % n_data != 0:
            ds = self._pad_minibatch(ds, batch_n, n_data)

        def _put(a):
            # host arrays go to device_put directly so each shard is
            # sliced on host and copied straight to its device; the
            # dtype cast runs on device, sharded (np can't even
            # represent bf16)
            if not isinstance(a, jax.Array):
                a = np.asarray(a)
            out = jax.device_put(a, batch_sharding)
            return out if out.dtype == dtype else out.astype(dtype)

        if self._is_graph:
            def _aslist(v):
                if v is None:
                    return None
                if isinstance(v, (list, tuple)):
                    return [
                        _put(a) if a is not None else None for a in v
                    ]
                return [_put(v)]

            x = _aslist(ds.features)
            y = _aslist(ds.labels)
            mask = _aslist(getattr(ds, "labels_masks", None)
                           or getattr(ds, "labels_mask", None))
            fmask = _aslist(getattr(ds, "features_masks", None)
                            or getattr(ds, "features_mask", None))
            has_masks = any(
                a is not None for a in (mask or []) + (fmask or [])
            )
        else:
            x = _put(ds.features)
            y = _put(ds.labels)
            mask = getattr(ds, "labels_mask", None)
            fmask = getattr(ds, "features_mask", None)
            mask = _put(mask) if mask is not None else None
            fmask = _put(fmask) if fmask is not None else None
            has_masks = mask is not None or fmask is not None
        return PlacedDataSet(
            features=x, labels=y, labels_mask=mask,
            features_mask=fmask, num_rows=batch_n,
            has_masks=has_masks,
        )

    def place_chunk(self, batches):
        """Stack k same-shaped minibatches into one [k, b, ...] block
        and scatter it onto the mesh with ``P(None, "data")`` in ONE
        ``device_put`` per array — the megastep feed's placement
        (each step's [b, ...] slice lands in exactly the per-step
        ``P("data")`` layout). Run on the prefetch worker via
        ``PrefetchIterator(megastep=K, chunk_placement=
        trainer.place_chunk)`` it double-buffers the feed: the next
        block's host->device copy overlaps the current fused
        dispatch. Accepts a list of host DataSets or a
        ``ChunkedDataSet``; single-input models only (the chunking
        adapter passes multi-input batches through per-step)."""
        from deeplearning4j_tpu.datasets.api import (
            ChunkedDataSet, PlacedChunk,
        )

        if isinstance(batches, PlacedChunk):
            return batches
        if isinstance(batches, ChunkedDataSet):
            batches = batches.to_datasets()
        batches = list(batches)
        m = self.model
        dtype = jnp.dtype(m.conf.dtype)
        n_data = self.mesh.shape["data"]
        batch_n = int(np.shape(batches[0].features)[0])
        k_accum = int(getattr(m, "grad_accum", 1))
        if k_accum > 1 and batch_n % (k_accum * n_data) != 0:
            raise ValueError(
                f"grad_accum={k_accum} on a {n_data}-wide data mesh "
                f"needs the batch to split into {k_accum} "
                f"microbatches of whole shards; got batch size "
                f"{batch_n} (make it a multiple of "
                f"{k_accum * n_data})"
            )
        if batch_n % n_data != 0:
            # pad-and-mask every step of the block (all share the
            # shape — the chunking adapter groups by signature)
            batches = [
                self._pad_minibatch(b, batch_n, n_data)
                for b in batches
            ]
        rows = batch_n * len(batches)
        chunk_sharding = NamedSharding(self.mesh, P(None, "data"))

        def stack(get):
            first = get(batches[0])
            if first is None:
                return None
            h = np.stack([np.asarray(get(b)) for b in batches])
            out = jax.device_put(h, chunk_sharding)
            return out if out.dtype == dtype else out.astype(dtype)

        x = stack(lambda b: b.features)
        y = stack(lambda b: b.labels)
        lm = stack(lambda b: getattr(b, "labels_mask", None))
        fm = stack(lambda b: getattr(b, "features_mask", None))
        if self._is_graph:
            # the DAG engine's score_fn takes per-slot lists
            x, y = [x], [y]
            lm = None if lm is None else [lm]
            fm = None if fm is None else [fm]
        return PlacedChunk(
            features=x, labels=y, labels_mask=lm,
            features_mask=fm, num_rows=rows,
        )

    # -- public API -----------------------------------------------------

    def fit(self, iterator, epochs: int = 1,
            prefetch: Optional[int] = None,
            grad_accum: Optional[int] = None,
            megastep: Optional[int] = None,
            validator=None, quarantine=None) -> list:
        """Fit ``epochs`` passes of ``iterator``, pipelined: batch
        materialization + sharded placement can run on a prefetch
        thread (``prefetch=N`` wraps the iterator in a depth-N
        ``PrefetchIterator`` with this trainer's placement; an
        already-wrapped iterator is used as-is), and dispatch runs
        through an ``AsyncDispatchWindow`` — up to ``max_in_flight``
        steps in flight, guard flags collected ``guard_lag`` steps
        late. The trajectory is bitwise identical to the synchronous
        per-step loop (tier-1-asserted on both engines).

        Returns the per-epoch mean scores (one float per epoch; the
        single device sync per epoch happens at the epoch boundary).
        ``iterator.reset()`` runs in a ``finally`` per epoch, so an
        exception that unwinds mid-epoch leaves the iterator rewound
        and a retried epoch starts from the top, not mid-stream.

        ``validator`` (a ``datasets.BatchValidator``, or the model's
        installed ``set_batch_validator`` one by default) screens every
        batch before it reaches the step; offenders are quarantined to
        ``quarantine`` (a ``datasets.QuarantineStore``) and skipped
        without advancing ``iteration_count``, so the defended
        trajectory over the surviving batches is bitwise the clean
        run's. With ``prefetch`` the validation runs on the prefetch
        worker thread."""
        from deeplearning4j_tpu.parallel import control_plane
        from deeplearning4j_tpu.parallel.dispatch import (
            AsyncDispatchWindow,
        )
        from deeplearning4j_tpu.resilience import preemption

        m = self.model
        if grad_accum is not None:
            # in-jit microbatch accumulation (core.accum_grad_step);
            # _step_for notices the knob change and rebuilds the step
            core.set_grad_accum(m, grad_accum)
        if megastep is not None:
            # K fused steps per dispatch (core.build_megastep); the
            # knob persists on the model like grad_accum
            core.set_transforms(m, megastep=megastep)
        use_mega = self._can_megastep()
        if validator is None:
            validator = getattr(m, "_batch_validator", None)
        if validator is not None:
            from deeplearning4j_tpu.datasets.validate import (
                ValidatingIterator,
            )

            if quarantine is None:
                quarantine = getattr(m, "_quarantine_store", None)
            if not isinstance(iterator, ValidatingIterator):
                iterator = ValidatingIterator(
                    iterator, validator, quarantine=quarantine,
                )
        source = iterator
        owned_prefetch = None
        if prefetch is not None and int(prefetch) > 0:
            from deeplearning4j_tpu.datasets.prefetch import (
                PrefetchIterator,
            )

            if not isinstance(iterator, PrefetchIterator):
                # under megastep the worker assembles whole K-blocks
                # and place_chunk scatters each while the previous
                # block's fused dispatch runs (double-buffered feed)
                source = owned_prefetch = PrefetchIterator(
                    iterator, queue_depth=int(prefetch),
                    placement=self.place_minibatch,
                    megastep=(
                        int(m.megastep) if use_mega else 1
                    ),
                    chunk_placement=self.place_chunk,
                )
        window = AsyncDispatchWindow(
            model=m, guard_fn=lambda: self.divergence_guard,
            on_restore=self._place_params,
            max_in_flight=self.max_in_flight,
            guard_lag=self.guard_lag,
        )
        epoch_scores = []
        tracer = get_tracer()
        fit_span = tracer.start_span(
            "train.fit",
            attrs={"epochs": int(epochs),
                   "engine": type(m).__name__,
                   "max_in_flight": int(self.max_in_flight)},
        )
        try:
            for epoch_i in range(epochs):
                epoch_span = tracer.start_span(
                    "train.epoch", parent=fit_span.context,
                    attrs={"epoch": int(m.epoch_count)},
                )
                self._epoch_span = epoch_span
                for listener in m.listeners:
                    if hasattr(listener, "on_epoch_start"):
                        listener.on_epoch_start(m)
                scores = []
                try:
                    if use_mega:
                        scores = self._fit_epoch_megastep(
                            source, window
                        )
                    else:
                        for ds in iter(source):
                            # preemption notice -> drain window +
                            # shut down the prefetch worker +
                            # emergency checkpoint, then
                            # PreemptedException
                            preemption.check_fit(
                                m, window=window,
                                prefetch=source
                                if hasattr(source, "shutdown")
                                else None,
                            )
                            control_plane.check_fit(m)
                            scores.append(
                                self.fit_minibatch(ds, _window=window)
                            )
                    window.drain()  # guard aborts surface here
                finally:
                    if hasattr(source, "reset"):
                        source.reset()
                epoch_scores.append(
                    float(jnp.mean(jnp.stack(scores)))
                    if scores else float("nan")
                )
                for listener in m.listeners:
                    if hasattr(listener, "on_epoch_end"):
                        listener.on_epoch_end(m)
                m.epoch_count += 1
                epoch_span.set_attr("score", epoch_scores[-1])
                epoch_span.end()
                self._epoch_span = None
        except BaseException as e:
            window.abandon()  # keep the original exception
            span, self._epoch_span = self._epoch_span, None
            if span is not None:
                span.end(status=type(e).__name__)
            fit_span.end(status=type(e).__name__)
            raise
        finally:
            if owned_prefetch is not None:
                owned_prefetch.shutdown()
        fit_span.end()
        return epoch_scores

    def fit_minibatch(self, ds, _window=None) -> float:
        m = self.model
        prof = profiler.get_active_profiler()
        if prof is not None:
            span = self._epoch_span
            prof.begin_step(
                m.iteration_count + 1,
                parent=span.context if span is not None else None,
            )
        placed = self.place_minibatch(ds)
        x, y = placed.features, placed.labels
        mask, fmask = placed.labels_mask, placed.features_mask
        step = self._step_for(bool(placed.has_masks))
        lrs = m.updater_def.scheduled_lrs(m.iteration_count)
        t = jnp.asarray(m.iteration_count + 1, jnp.float32)
        rng = jax.random.fold_in(m._base_key, m.iteration_count)
        extra = (
            (core.ensure_loss_scale_state(m),) if self._built_ls
            else ()
        )
        if self._built_sg:
            extra = extra + (core.ensure_stat_guard_state(m),)
        out = step(
            m.params, m.updater_state, m.state, x, y, mask, fmask,
            {k: jnp.asarray(v, jnp.float32) for k, v in lrs.items()},
            t, rng, *extra,
        )
        guard = self.divergence_guard
        m.params, m.updater_state, m.state = out[:3]
        score = out[3]
        i = 4
        if self._built_telemetry:
            m._last_grad_norm = out[i]  # device scalar; lazy
            i += 1
        if self._built_ls:
            m._loss_scale_state = out[i]
            i += 1
        if self._built_sg:
            m._stat_guard_state = out[i]
            i += 1
        ok = out[i] if guard is not None else None
        m._last_batch_rows = placed.num_rows  # examples/sec signal
        m.iteration_count += 1
        m.score_value = score  # lazy; reading syncs
        if _window is not None:
            # async path (fit): flag collected guard_lag steps late,
            # completion awaited max_in_flight steps late
            _window.push(score, ok)
        elif guard is not None:
            if bool(ok):  # device sync — the cost of supervision
                guard.good_step()
            else:
                # in-jit select already suppressed the update; the
                # guard now applies skip/rollback policy host-side
                guard.bad_step(m, on_restore=self._place_params)
        if m.listeners:
            lt0 = time.perf_counter()
            for listener in m.listeners:
                listener.iteration_done(m, m.iteration_count)
            if prof is not None:
                prof.note_listener_ms(
                    (time.perf_counter() - lt0) * 1e3
                )
        if hasattr(m, "_reset_recurrent_state"):
            m._reset_recurrent_state()
        if prof is not None:
            prof.end_step(
                model=m, ds=ds, score=score,
                grad_norm=getattr(m, "_last_grad_norm", None),
                rows=placed.num_rows,
            )
        return score  # 0-d device array; float() to sync

    def _fit_epoch_megastep(self, source, window) -> list:
        """One megastep epoch: group the stream into K-blocks (or
        consume pre-assembled ``ChunkedDataSet``/``PlacedChunk``
        payloads from a chunk-mode prefetch) and run each as one
        fused dispatch via ``fit_megachunk``; shape-changing or
        trailing partials fall back to the per-step program — same
        math, so the mixed trajectory stays bitwise. Chunk boundaries
        are the preemption-checkpoint boundaries (staleness <= K-1
        steps)."""
        from deeplearning4j_tpu.datasets.api import (
            ChunkedDataSet, PlacedChunk, PlacedDataSet,
        )
        from deeplearning4j_tpu.datasets.prefetch import _chunk_sig
        from deeplearning4j_tpu.parallel import control_plane
        from deeplearning4j_tpu.resilience import preemption

        m = self.model
        k_target = int(m.megastep)
        scores = []
        buf = []
        sig = None

        def flush():
            nonlocal buf
            if len(buf) == 1:
                scores.append(self.fit_minibatch(buf[0], _window=window))
            elif buf:
                # the chunk's guard flags are applied synchronously
                # from its readback: settle the per-step backlog
                # first so guard bookkeeping stays ordered
                window.drain()
                scores.append(self.fit_megachunk(self.place_chunk(buf)))
            buf = []

        for ds in iter(source):
            preemption.check_fit(
                m, window=window,
                prefetch=source
                if hasattr(source, "shutdown") else None,
            )
            control_plane.check_fit(m)
            if isinstance(ds, (ChunkedDataSet, PlacedChunk)):
                flush()
                sig = None
                if ds.k >= 2:
                    window.drain()
                    scores.append(self.fit_megachunk(ds))
                else:
                    for b in ds.to_datasets():
                        scores.append(
                            self.fit_minibatch(b, _window=window)
                        )
                continue
            if isinstance(ds, PlacedDataSet) or isinstance(
                ds.features, (list, tuple)
            ):
                # already-placed singles (chunk-mode passthrough) and
                # multi-input batches take the per-step program
                flush()
                sig = None
                scores.append(self.fit_minibatch(ds, _window=window))
                continue
            s = _chunk_sig(ds)
            if buf and s != sig:
                flush()
            sig = s
            buf.append(ds)
            if len(buf) >= k_target:
                flush()
        flush()
        return scores

    def fit_megachunk(self, chunk) -> float:
        """One fused K-step dispatch from a placed (or host-stacked)
        block. Returns the block's last score as a host float — the
        chunk's single readback already paid that sync."""
        from deeplearning4j_tpu.datasets.api import PlacedChunk

        step = self._megastep_for()  # may refresh the _built_* flags
        if not isinstance(chunk, PlacedChunk):
            chunk = self.place_chunk(chunk)
        m = self.model
        extra = (
            (core.ensure_loss_scale_state(m),) if self._built_ls
            else ()
        )
        if self._built_sg:
            extra = extra + (core.ensure_stat_guard_state(m),)
        core.run_megastep_chunk(
            m,
            (chunk.features, chunk.labels, chunk.labels_mask,
             chunk.features_mask, chunk.k),
            step_fn=step, extra=extra,
            guard=self.divergence_guard,
            on_restore=self._place_params,
            rows=chunk.num_rows,
            ls_active=self._built_ls, sg_active=self._built_sg,
        )
        m._last_batch_rows = chunk.num_rows
        return float(m._last_score)

    def set_divergence_guard(self, guard) -> None:
        """(Un)install a resilience.DivergenceGuard; the jitted steps
        are rebuilt on next use because the guarded step has an extra
        ok-flag output."""
        self.divergence_guard = guard
        self.model._ckpt_guard = guard
        self._jit_step_sm = None
        self._jit_step_gspmd = None
        self._jit_megastep_dist = None

    def resume(self, source, load_updater: bool = True) -> int:
        """Resume training from a checkpoint: restore params, updater
        state, layer state, and the step counter into this trainer's
        model, then re-place everything onto the mesh with the
        trainer's shardings (the broadcast step, done once — same as
        construction). ``source`` is a resilience.CheckpointManager
        (newest restorable version, with corrupted-newest fallback) or
        a checkpoint zip path. Returns the restored step so callers
        can skip already-consumed batches:

            trainer = DistributedTrainer(model, mesh)
            step = trainer.resume(manager)
            trainer.fit(iterator_from(step), epochs=...)

        Continuation is exact: the per-step PRNG folds
        ``iteration_count`` into the model's seed-derived base key and
        lr schedules/updater ``t`` derive from the same counter, so a
        restored run replays the identical trajectory the uninterrupted
        run would have taken (tier-1-tested in
        ``tests/test_resilience.py``)."""
        from deeplearning4j_tpu.resilience.checkpoint import restore_into

        _, step = restore_into(
            self.model, source, load_updater=load_updater
        )
        self._place_params()
        return step
