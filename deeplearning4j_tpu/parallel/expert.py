"""Expert parallelism: Switch-style top-1 mixture-of-experts with the
experts sharded over an ``expert`` mesh axis and tokens exchanged via
``lax.all_to_all``.

Net-new capability vs the reference (SURVEY.md §2.4: no EP), designed
TPU-first: routing builds a static-shape dispatch tensor
(position-in-expert cumsum, capacity-clipped — the Switch Transformer
dispatch), tokens hop to their expert's device with ONE all_to_all over
ICI, each device runs only its local experts' FFN on [capacity] tokens,
and a second all_to_all brings results home. Dropped tokens (over
capacity) pass through as zeros, exactly like the reference
formulation of Switch.

``moe_ffn_reference`` is the single-device dense-dispatch semantics the
sharded path must reproduce bit-for-bit; the load-balancing auxiliary
loss is the standard E * sum(f_e * p_e).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.compat import shard_map_compat as _shard_map


def build_expert_mesh(n_devices: int = None, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=("expert",))


def init_moe_params(key, d_model: int, d_hidden: int, n_experts: int,
                    dtype=jnp.float32) -> dict:
    kg, kw1, kb1, kw2, kb2 = jax.random.split(key, 5)
    scale_in = 1.0 / jnp.sqrt(jnp.asarray(d_model, dtype))
    scale_h = 1.0 / jnp.sqrt(jnp.asarray(d_hidden, dtype))
    return {
        "router": jax.random.normal(kg, (d_model, n_experts), dtype)
        * scale_in,
        "w1": jax.random.normal(
            kw1, (n_experts, d_model, d_hidden), dtype) * scale_in,
        "b1": jnp.zeros((n_experts, d_hidden), dtype),
        "w2": jax.random.normal(
            kw2, (n_experts, d_hidden, d_model), dtype) * scale_h,
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def switch_dispatch(logits: jax.Array, capacity: int,
                    token_mask: jax.Array = None
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 dispatch tensors (Switch Transformer routing).

    logits [n, E] -> (dispatch [n, E, C] one-hot, combine [n, E, C]
    gate-weighted, probs [n, E]). Tokens past an expert's capacity C
    are dropped (all-zero rows in dispatch). ``token_mask`` [n] marks
    valid tokens; masked (padding) tokens neither consume capacity nor
    receive expert output."""
    n, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)              # [n]
    gate = jnp.max(probs, axis=-1)                       # [n]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=logits.dtype)
    if token_mask is not None:
        onehot = onehot * token_mask[:, None].astype(onehot.dtype)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot            # [n, E]
    pos = jnp.sum(pos, axis=-1) - 1.0                    # [n]
    keep = pos < capacity
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=logits.dtype)          # [n, C]
    dispatch = (
        onehot[:, :, None] * pos_oh[:, None, :]
        * keep[:, None, None].astype(logits.dtype)
    )
    combine = dispatch * gate[:, None, None]
    return dispatch, combine, probs


def _expert_ffn(w1, b1, w2, b2, x):
    """[E_local, C_total, d] tokens through per-expert 2-layer FFN."""
    h = jax.nn.relu(
        jnp.einsum("ecd,edh->ech", x, w1) + b1[:, None, :]
    )
    return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]


def moe_ffn_reference(params: dict, x: jax.Array,
                      capacity_factor: float = 1.25,
                      token_mask: jax.Array = None) -> jax.Array:
    """Single-device dense-dispatch Switch MoE: the semantics the
    sharded path must match (capacity drops included). Masked tokens
    produce zero output and consume no capacity."""
    n, d = x.shape
    e = params["router"].shape[1]
    capacity = max(int(np.ceil(n * capacity_factor / e)), 1)
    logits = x @ params["router"]
    dispatch, combine, _ = switch_dispatch(logits, capacity, token_mask)
    expert_in = jnp.einsum("nd,nec->ecd", x, dispatch)   # [E, C, d]
    expert_out = _expert_ffn(
        params["w1"], params["b1"], params["w2"], params["b2"],
        expert_in,
    )
    return jnp.einsum("ecd,nec->nd", expert_out, combine)


def aux_load_balance_loss(logits: jax.Array) -> jax.Array:
    """Switch load-balancing loss E * sum_e f_e * p_e (f_e = fraction
    of tokens routed to e, p_e = mean router prob)."""
    probs = jax.nn.softmax(logits, axis=-1)
    e = probs.shape[-1]
    assign = jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=probs.dtype)
    f = jnp.mean(assign, axis=0)
    p = jnp.mean(probs, axis=0)
    return e * jnp.sum(f * p)


class ExpertParallelMoE:
    """Mesh-sharded Switch MoE (the EP runtime): experts live
    stacked on axis 0 sharded over 'expert'; tokens stay data-sharded
    on the same axis and travel through two all_to_alls."""

    def __init__(self, mesh: Mesh, n_experts: int,
                 capacity_factor: float = 1.25,
                 axis_name: str = "expert"):
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_devices = mesh.shape[axis_name]
        if n_experts % self.n_devices:
            raise ValueError(
                f"{n_experts} experts not divisible over "
                f"{self.n_devices} devices"
            )
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self._jit_applies: dict = {}      # token count -> compiled fn
        self._jit_train_steps: dict = {}  # token count -> compiled fn

    def shard_params(self, params: dict) -> dict:
        rep = NamedSharding(self.mesh, P())
        exp = NamedSharding(self.mesh, P(self.axis_name))
        out = {"router": jax.device_put(params["router"], rep)}
        for k in ("w1", "b1", "w2", "b2"):
            out[k] = jax.device_put(params[k], exp)
        return out

    def _build(self, n_tokens: int, with_aux: bool = False):
        """Sharded apply. ``with_aux=True`` additionally returns the
        per-device load-balance loss ([n_devices] vector — the Switch
        formulation balances each device's own token shard) computed
        from the SAME router logits the dispatch uses, so training
        never re-runs the router matmul outside the shard_map."""
        axis = self.axis_name
        nd = self.n_devices
        e_total = self.n_experts
        e_local = e_total // nd
        n_local = n_tokens // nd
        capacity = max(
            int(np.ceil(n_local * self.capacity_factor / e_total)), 1
        )

        def local(router, w1, b1, w2, b2, x):
            # x [n_local, d]; router replicated; experts local [e_local,...]
            logits = x @ router
            dispatch, combine, _ = switch_dispatch(logits, capacity)
            expert_in = jnp.einsum("nd,nec->ecd", x, dispatch)
            # [E, C, d] -> exchange so each device holds, for its OWN
            # e_local experts, the token slices from every peer:
            # [E, C, d] = [nd * e_local, C, d] --all_to_all--> same
            # shape, rows now (peer, local expert)
            shuf = jax.lax.all_to_all(
                expert_in.reshape(nd, e_local * capacity, -1),
                axis, split_axis=0, concat_axis=0, tiled=False,
            )  # [nd, e_local*C, d] rows = source peers
            shuf = shuf.reshape(nd, e_local, capacity, -1)
            shuf = shuf.transpose(1, 0, 2, 3).reshape(
                e_local, nd * capacity, -1
            )
            out = _expert_ffn(w1, b1, w2, b2, shuf)
            # reverse the exchange
            out = out.reshape(e_local, nd, capacity, -1)
            out = out.transpose(1, 0, 2, 3).reshape(
                nd, e_local * capacity, -1
            )
            back = jax.lax.all_to_all(
                out, axis, split_axis=0, concat_axis=0, tiled=False,
            ).reshape(e_total, capacity, -1)
            y = jnp.einsum("ecd,nec->nd", back, combine)
            if with_aux:
                return y, aux_load_balance_loss(logits)[None]
            return y

        sm = _shard_map()(
            local, mesh=self.mesh,
            in_specs=(P(), P(axis), P(axis), P(axis), P(axis),
                      P(axis)),
            out_specs=(P(axis), P(axis)) if with_aux else P(axis),
            check_rep=False,
        )

        def apply(params, x):
            return sm(
                params["router"], params["w1"], params["b1"],
                params["w2"], params["b2"], x,
            )

        return apply

    def apply(self, params: dict, x) -> jax.Array:
        """x [n_tokens, d], n_tokens divisible by the device count;
        tokens sharded over 'expert' (placed if not already). One
        compile per distinct token count, all kept."""
        x, n = self._check_tokens(x)
        fn = self._jit_applies.get(n)
        if fn is None:
            fn = jax.jit(self._build(n))
            self._jit_applies[n] = fn
        return fn(params, x)

    def train_step(self, params: dict, x, targets, *, lr=0.05,
                   aux_weight: float = 0.01):
        """One synchronous SGD training step through the sharded MoE:
        ``loss = mean((moe(x) - targets)^2) + aux_weight *
        load_balance`` (the Switch auxiliary loss on the router
        logits). Returns ``(new_params, loss)``.

        This is the public EP training API — gradients flow through
        both all_to_alls and the per-expert FFNs; callers (the driver
        dryrun, tests) never touch compiled internals. ``lr`` and
        ``aux_weight`` are traced scalars, so one compile per token
        count serves every hyperparameter setting."""
        x, n = self._check_tokens(x)
        exp = NamedSharding(self.mesh, P(self.axis_name))
        targets = jax.device_put(jnp.asarray(targets), exp)
        fn = self._jit_train_steps.get(n)
        if fn is None:
            apply = self._build(n, with_aux=True)

            def step(p, x_, tgt, lr_, aux_w):
                def loss_fn(pp):
                    out, aux = apply(pp, x_)
                    main = jnp.mean((out - tgt) ** 2)
                    return main + aux_w * jnp.mean(aux)

                loss, grads = jax.value_and_grad(loss_fn)(p)
                # dtype-preserving update: bf16 params stay bf16
                new = jax.tree_util.tree_map(
                    lambda a, g: a - (lr_ * g).astype(a.dtype), p, grads
                )
                return new, loss

            fn = jax.jit(step)
            self._jit_train_steps[n] = fn
        return fn(params, x, targets, jnp.float32(lr),
                  jnp.float32(aux_weight))

    def _check_tokens(self, x):
        x = jnp.asarray(x)
        n = x.shape[0]
        if n % self.n_devices:
            raise ValueError(
                f"{n} tokens not divisible by {self.n_devices} devices"
            )
        x = jax.device_put(
            x, NamedSharding(self.mesh, P(self.axis_name))
        )
        return x, n
