"""Cross-host elastic control plane: lease-based membership, epoch
fencing, and step-boundary barriers for multi-process training.

Every robustness mechanism below this module — heartbeats, snapshot
rings, ZeRO re-sharding — lives inside one process and dies with it.
This module is the cross-host rung: a tiny TCP **coordinator**
(:class:`LeaseCoordinator`) grants epoch-fenced membership **leases**
to per-process **worker agents** (:class:`WorkerAgent`), following the
coordinator/worker failure model of the TensorFlow system paper
(PAPERS.md, arxiv 1605.08695) and the reference's Spark master/worker
liveness:

- **Leases, not sessions.** A member holds the mesh only while it
  keeps renewing a time-bounded lease (renewals ride
  ``resilience/retry.py`` with bounded backoff). A missed lease
  declares the host dead; there is no graceful-disconnect
  requirement, so SIGKILL and network partition look identical.
- **Epoch fencing.** Every membership change bumps the **epoch**.
  Requests stamped with a stale epoch are rejected with the current
  recovery plan, and a declared-dead member is *fenced*: its old
  identity can never act again (zombie writes from a paused/partitioned
  host cannot corrupt the new mesh). A fenced host may rejoin — as a
  *fresh* member admitted at the next epoch bump.
- **Step barriers.** Workers arrive at a barrier at every step
  boundary; the coordinator releases it when every current member has
  arrived. Arrival renews the lease, so a worker blocked on slow
  peers never expires. A death observed while others wait converts
  the barrier into a recovery plan for the survivors — all of whom
  therefore agree on the recovery point.
- **Recovery plans.** The coordinator answers a stale epoch with a
  :class:`RecoveryPlan`: the new epoch/term, the survivor set in rank
  order, and a fresh ``jax.distributed`` coordinator address (new
  term, fresh port — a half-dead runtime never gets reused). The
  training side of recovery (snapshot rollback, mesh re-formation,
  ZeRO re-shard) lives in ``parallel/elastic.HostElasticTrainer``.
- **Graceful degradation.** Coordinator loss is detected by retry
  exhaustion (:class:`CoordinatorLostException`); the fit driver
  checkpoints and exits with the preemption exit codes (75/76)
  rather than hang or train a partitioned brain.

The protocol is line-delimited JSON over TCP — one request per
connection, no long-lived sockets to leak into forked children — and
the state machine (:class:`LeaseState`) is pure and clock-injectable
so the fencing/expiry/rejoin logic is unit-testable under a fake
clock with no sockets or threads (``LocalTransport``).
"""

from __future__ import annotations

import json
import logging
import os
import random
import socket
import socketserver
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.exceptions import (
    DL4JFaultException, DeadlineExceededException,
    RetryExhaustedException,
)
from deeplearning4j_tpu.observability import flightrec
from deeplearning4j_tpu.resilience.retry import RetryPolicy, retry_call

logger = logging.getLogger(__name__)


def _default_registry():
    from deeplearning4j_tpu.observability.metrics import default_registry

    return default_registry()


class ControlPlaneException(DL4JFaultException):
    """Base for control-plane faults."""


class CoordinatorLostException(ControlPlaneException):
    """The control coordinator became unreachable (retries exhausted).
    Membership truth is gone: the fit driver checkpoints and exits
    with the preemption exit codes instead of hanging."""


class HostFencedException(ControlPlaneException):
    """This member was declared dead and fenced out of the epoch. Its
    training state is a zombie's — it must NOT be checkpointed or
    pushed anywhere; the process may only rejoin as a fresh member."""


@dataclass(frozen=True)
class RecoveryPlan:
    """What the coordinator hands a survivor at an epoch bump: the new
    membership in rank order plus a fresh ``jax.distributed``
    coordinator address for the re-formed runtime."""

    epoch: int
    term: int
    members: Tuple[int, ...]
    num: int
    jax_coordinator: str
    member: Optional[int] = None   # the recipient's member id
    rank: Optional[int] = None     # ... and its rank in the new mesh
    dead: Tuple[int, ...] = ()
    admitted: Tuple[int, ...] = ()
    lease_s: float = 2.0

    @classmethod
    def from_dict(cls, d: dict) -> "RecoveryPlan":
        return cls(
            epoch=int(d["epoch"]), term=int(d["term"]),
            members=tuple(int(m) for m in d["members"]),
            num=int(d["num"]), jax_coordinator=str(d["jax_coordinator"]),
            member=(None if d.get("member") is None
                    else int(d["member"])),
            rank=None if d.get("rank") is None else int(d["rank"]),
            dead=tuple(int(m) for m in d.get("dead", ())),
            admitted=tuple(int(m) for m in d.get("admitted", ())),
            lease_s=float(d.get("lease_s", 2.0)),
        )


def _ephemeral_port(host: str = "127.0.0.1") -> int:
    """Bind-and-release port pick for the NEXT jax coordinator. The
    release-to-bind window is racy by nature; consumers retry the
    bring-up (``init_distributed_elastic``) rather than trust the
    reservation."""
    s = socket.socket()
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


class LeaseState:
    """The coordinator's pure state machine: membership, leases,
    epochs, fences, barriers. Clock-injectable and lock-protected;
    contains no sockets or threads of its own, so every transition is
    unit-testable under a fake clock.

    Lifecycle: ``expected`` members join during epoch 0 (formation;
    leases are not swept until the mesh has formed once). When the
    last one arrives the state *reforms* — epoch/term bump to 1, a
    plan is published, everyone gets a fresh lease. From then on any
    expiry, graceful leave, or admitted rejoin reforms again: new
    epoch, new term, fresh ``jax_coordinator`` port, fences for the
    dead."""

    def __init__(self, num_processes: int, *, lease_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic,
                 host: str = "127.0.0.1",
                 port_factory: Optional[Callable[[], int]] = None,
                 admit_joins: bool = True, registry=None):
        if num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        if lease_s <= 0:
            raise ValueError("lease_s must be > 0")
        self.expected = int(num_processes)
        self.lease_s = float(lease_s)
        self.clock = clock
        self.host = host
        self.admit_joins = bool(admit_joins)
        self._port_factory = port_factory or (
            lambda: _ephemeral_port(host))
        self.epoch = 0
        self.term = 0
        self.members: Dict[int, float] = {}   # member id -> lease expiry
        self.pending: List[int] = []          # joins awaiting admission
        self.fenced: set = set()              # dead member ids (sticky)
        self.plan: Optional[dict] = None      # current epoch's plan
        self._arrived: Dict[int, set] = {}    # barrier step -> member ids
        self._synced: Dict[str, Dict[int, object]] = {}  # key -> payloads
        self._next_id = 0
        self.cond = threading.Condition()
        registry = registry if registry is not None else _default_registry()
        self._m_renewals = registry.counter(
            "lease_renewals_total",
            help="successful membership lease renewals",
        )._default()
        self._m_expired = registry.counter(
            "lease_expired_total",
            help="membership leases expired (host declared dead)",
            labels=("shard",),
        )
        self._m_epoch = registry.gauge(
            "control_epoch",
            help="current control-plane membership epoch",
        )._default()

    # -- internals (caller holds self.cond) -----------------------------

    def _sweep_locked(self) -> None:
        if self.epoch == 0:
            return  # formation grace: nobody expires before first form
        now = self.clock()
        expired = sorted(m for m, exp in self.members.items()
                         if exp <= now)
        if not expired:
            return
        for m in expired:
            del self.members[m]
            self.fenced.add(m)
            self._m_expired.labels(str(m)).inc()
            logger.warning(
                "control plane: member %d lease expired at epoch %d "
                "— declared dead and fenced", m, self.epoch)
            flightrec.record_event("lease_expired", member=m,
                                   epoch=self.epoch)
        self._reform_locked(dead=expired)

    def _reform_locked(self, dead: Sequence[int] = ()) -> None:
        admitted = []
        while self.pending:
            m = self.pending.pop(0)
            admitted.append(m)
            self.members[m] = 0.0  # expiry set below
        self.epoch += 1
        self.term += 1
        self._m_epoch.set(float(self.epoch))
        if not self.members:
            self.plan = None
            self.cond.notify_all()
            return
        fresh = self.clock() + self.lease_s
        for m in self.members:
            self.members[m] = fresh
        order = sorted(self.members)
        self.plan = {
            "epoch": self.epoch, "term": self.term, "members": order,
            "num": len(order),
            "jax_coordinator": "%s:%d" % (self.host,
                                          int(self._port_factory())),
            "dead": sorted(int(m) for m in dead),
            "admitted": admitted, "lease_s": self.lease_s,
        }
        self._arrived = {}
        self._synced = {}
        flightrec.record_event(
            "control_epoch", epoch=self.epoch, term=self.term,
            num=len(order), dead=self.plan["dead"], admitted=admitted)
        self.cond.notify_all()

    def _plan_for_locked(self, member: int) -> dict:
        plan = dict(self.plan)
        plan["member"] = member
        plan["rank"] = self.plan["members"].index(member)
        return plan

    # -- membership ------------------------------------------------------

    def join(self, member_hint: Optional[int] = None) -> int:
        """Register a joiner; returns its member id. During formation
        (epoch 0) a free ``member_hint`` is honored so ranks can keep
        their launcher-assigned ids; after formation every joiner —
        including a fenced host coming back — is a FRESH member queued
        for admission at the next epoch bump."""
        with self.cond:
            self._sweep_locked()
            if self.epoch == 0 and len(self.members) < self.expected:
                if (member_hint is not None
                        and int(member_hint) not in self.members):
                    mid = int(member_hint)
                else:
                    mid = self._next_id
                self._next_id = max(self._next_id, mid + 1)
                self.members[mid] = self.clock() + self.lease_s
                if len(self.members) == self.expected:
                    self._reform_locked()
                else:
                    self.cond.notify_all()
                return mid
            mid = self._next_id
            self._next_id += 1
            self.pending.append(mid)
            flightrec.record_event("member_join_pending", member=mid,
                                   epoch=self.epoch)
            self.cond.notify_all()
            return mid

    def grant_for(self, member: int) -> Optional[dict]:
        """The member's current grant: ``None`` while the mesh is
        still forming or the member awaits admission; a fence error
        once declared dead; otherwise the personalized plan."""
        with self.cond:
            self._sweep_locked()
            if member in self.fenced:
                return {"ok": False, "error": "fenced",
                        "epoch": self.epoch}
            if self.plan is None or member not in self.members:
                return None
            out = self._plan_for_locked(member)
            out["ok"] = True
            return out

    def touch(self, member: int) -> None:
        """Refresh a live member's lease without an epoch check (used
        while it blocks on formation). Never resurrects."""
        with self.cond:
            if member in self.members:
                self.members[member] = self.clock() + self.lease_s

    def renew(self, member: int, epoch: int) -> dict:
        with self.cond:
            self._sweep_locked()
            if member in self.fenced or member not in self.members:
                return {"ok": False, "error": "fenced",
                        "epoch": self.epoch}
            if int(epoch) != self.epoch:
                # the member is alive, just behind: its renewal still
                # proves liveness, so extend the lease — a survivor
                # mid-recovery (slow jax re-formation) must not expire
                # because its renewals carry yesterday's epoch
                self.members[member] = self.clock() + self.lease_s
                return {"ok": False, "error": "stale_epoch",
                        "epoch": self.epoch,
                        "plan": self._plan_for_locked(member)}
            self.members[member] = self.clock() + self.lease_s
            self._m_renewals.inc()
            return {"ok": True, "epoch": self.epoch,
                    "lease_s": self.lease_s}

    def leave(self, member: int) -> dict:
        """Graceful departure: fence the identity and reform over the
        remainder (a planned downscale, minus the expiry wait)."""
        with self.cond:
            self._sweep_locked()
            if member in self.members:
                del self.members[member]
                self.fenced.add(member)
                self._reform_locked(dead=[member])
            return {"ok": True, "epoch": self.epoch}

    # -- barrier ---------------------------------------------------------

    def arrive(self, member: int, epoch: int, step: int) -> dict:
        """Non-blocking barrier arrival: returns a decision —
        ``proceed`` (everyone arrived), ``wait`` (peers outstanding),
        or an error (``fenced`` / ``stale_epoch`` + plan). Arrival
        renews the lease, so a member blocked on stragglers never
        expires; a pending join converts the boundary into an epoch
        bump so rejoiners are admitted between steps, never mid-step."""
        with self.cond:
            self._sweep_locked()
            if member in self.fenced or member not in self.members:
                return {"ok": False, "error": "fenced",
                        "epoch": self.epoch}
            if int(epoch) != self.epoch:
                return {"ok": False, "error": "stale_epoch",
                        "epoch": self.epoch,
                        "plan": self._plan_for_locked(member)}
            if self.pending and self.admit_joins:
                self._reform_locked()
                return {"ok": False, "error": "stale_epoch",
                        "epoch": self.epoch,
                        "plan": self._plan_for_locked(member)}
            self.members[member] = self.clock() + self.lease_s
            step = int(step)
            got = self._arrived.setdefault(step, set())
            got.add(member)
            if set(self.members) <= got:
                for s in [s for s in self._arrived if s < step]:
                    del self._arrived[s]
                self.cond.notify_all()
                return {"ok": True, "decision": "proceed",
                        "epoch": self.epoch, "step": step}
            return {"ok": True, "decision": "wait",
                    "epoch": self.epoch, "step": step}

    def barrier_wait(self, member: int, epoch: int, step: int,
                     timeout_s: float, poll_s: float = 0.05) -> dict:
        """Blocking barrier (real-clock server handlers only): poll
        :meth:`arrive` until it decides. Each poll renews the lease."""
        deadline = self.clock() + timeout_s
        poll_s = min(poll_s, self.lease_s / 4.0)
        while True:
            r = self.arrive(member, epoch, step)
            if r.get("decision") != "wait":
                return r
            with self.cond:
                if self.clock() >= deadline:
                    return {"ok": False, "error": "barrier_timeout",
                            "epoch": self.epoch, "step": step}
                self.cond.wait(poll_s)

    def sync(self, member: int, epoch: int, key: str,
             payload=None) -> dict:
        """Payload-carrying named barrier — the two-phase checkpoint
        commit fence. Like :meth:`arrive`, but each member brings a
        JSON payload (its shard digest) and ``proceed`` returns
        everyone's, so all ranks leave the barrier knowing every
        shard is durable before rank 0 writes the manifest. Keys are
        opaque strings in a namespace separate from step barriers,
        and — unlike ``arrive`` — a pending join does NOT bump the
        epoch here: sync barriers run off the step path (background
        checkpoint commits) and must not steal the admission point
        from the step barrier. Any epoch bump (death, admission)
        clears in-flight sync keys, so a commit can never span a
        membership change."""
        with self.cond:
            self._sweep_locked()
            if member in self.fenced or member not in self.members:
                return {"ok": False, "error": "fenced",
                        "epoch": self.epoch}
            if int(epoch) != self.epoch:
                return {"ok": False, "error": "stale_epoch",
                        "epoch": self.epoch,
                        "plan": self._plan_for_locked(member)}
            self.members[member] = self.clock() + self.lease_s
            key = str(key)
            got = self._synced.setdefault(key, {})
            got[member] = payload
            if set(self.members) <= set(got):
                # bounded: drop oldest completed keys (keep a few so
                # stragglers re-polling a just-released key still see
                # proceed; a straggler past that re-arrives, idempotent)
                while len(self._synced) > 8:
                    oldest = next(iter(self._synced))
                    if oldest == key:
                        break
                    del self._synced[oldest]
                self.cond.notify_all()
                return {"ok": True, "decision": "proceed",
                        "epoch": self.epoch, "key": key,
                        "payloads": {str(m): got[m]
                                     for m in sorted(got)}}
            return {"ok": True, "decision": "wait",
                    "epoch": self.epoch, "key": key}

    def sync_wait(self, member: int, epoch: int, key: str, payload,
                  timeout_s: float, poll_s: float = 0.05) -> dict:
        """Blocking :meth:`sync` (real-clock server handlers only)."""
        deadline = self.clock() + timeout_s
        poll_s = min(poll_s, self.lease_s / 4.0)
        while True:
            r = self.sync(member, epoch, key, payload)
            if r.get("decision") != "wait":
                return r
            with self.cond:
                if self.clock() >= deadline:
                    return {"ok": False, "error": "barrier_timeout",
                            "epoch": self.epoch, "key": key}
                self.cond.wait(poll_s)

    def join_wait(self, member_hint: Optional[int], timeout_s: float,
                  poll_s: float = 0.05) -> dict:
        """Blocking join (server handlers): register, then wait for
        formation/admission. Keeps the pre-formation lease fresh."""
        mid = self.join(member_hint)
        deadline = self.clock() + timeout_s
        while True:
            g = self.grant_for(mid)
            if g is not None:
                return g
            with self.cond:
                if self.clock() >= deadline:
                    return {"ok": False, "error": "join_timeout",
                            "member": mid, "epoch": self.epoch}
                self.touch(mid)
                self.cond.wait(poll_s)

    def info(self) -> dict:
        with self.cond:
            self._sweep_locked()
            return {"ok": True, "epoch": self.epoch, "term": self.term,
                    "members": sorted(self.members),
                    "pending": list(self.pending),
                    "fenced": sorted(self.fenced),
                    "expected": self.expected}


class LeaseCoordinator:
    """TCP front for :class:`LeaseState`: a threading server speaking
    one line-delimited JSON request per connection. Ops: ``join``
    (blocking until formation/admission), ``grant``, ``renew``,
    ``barrier`` (blocking), ``leave``, ``info``."""

    def __init__(self, num_processes: int, *, host: str = "127.0.0.1",
                 port: int = 0, lease_s: float = 2.0,
                 join_timeout_s: float = 60.0,
                 barrier_timeout_s: float = 120.0,
                 port_factory: Optional[Callable[[], int]] = None,
                 admit_joins: bool = True, registry=None):
        self.state = LeaseState(
            num_processes, lease_s=lease_s, host=host,
            port_factory=port_factory, admit_joins=admit_joins,
            registry=registry,
        )
        self.join_timeout_s = float(join_timeout_s)
        self.barrier_timeout_s = float(barrier_timeout_s)
        coordinator = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    line = self.rfile.readline()
                    if not line:
                        return
                    resp = coordinator._dispatch(
                        json.loads(line.decode("utf-8")))
                except Exception as e:  # never kill the server thread
                    logger.warning("control plane: bad request: %r", e)
                    resp = {"ok": False, "error": "coordinator_error",
                            "detail": str(e)[:200]}
                try:
                    self.wfile.write(
                        (json.dumps(resp) + "\n").encode("utf-8"))
                except Exception:
                    pass  # client went away mid-reply

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return "%s:%d" % (self.host, self.port)

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        st = self.state
        if op == "join":
            return st.join_wait(req.get("member"),
                                float(req.get("timeout_s",
                                              self.join_timeout_s)))
        if op == "grant":
            g = st.grant_for(int(req["member"]))
            if g is None:
                return {"ok": True, "decision": "wait",
                        "member": int(req["member"]),
                        "epoch": st.epoch}
            return g
        if op == "renew":
            return st.renew(int(req["member"]), int(req["epoch"]))
        if op == "barrier":
            return st.barrier_wait(
                int(req["member"]), int(req["epoch"]),
                int(req["step"]),
                float(req.get("timeout_s", self.barrier_timeout_s)))
        if op == "sync":
            return st.sync_wait(
                int(req["member"]), int(req["epoch"]),
                str(req["key"]), req.get("payload"),
                float(req.get("timeout_s", self.barrier_timeout_s)))
        if op == "leave":
            return st.leave(int(req["member"]))
        if op == "info":
            return st.info()
        return {"ok": False, "error": "bad_op", "op": str(op)[:40]}

    def start(self) -> "LeaseCoordinator":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="lease-coordinator",
            daemon=True)
        self._thread.start()
        logger.info("control plane: coordinator on %s (expecting %d)",
                    self.address, self.state.expected)
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "LeaseCoordinator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class TcpTransport:
    """One JSON request per fresh connection. Stateless between
    requests so chaos (drop/partition) and retries compose cleanly."""

    def __init__(self, address: str, *, timeout_s: float = 5.0):
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.timeout_s = float(timeout_s)

    def request(self, payload: dict,
                timeout_s: Optional[float] = None) -> dict:
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        with socket.create_connection((self.host, self.port),
                                      timeout=t) as s:
            s.settimeout(t)
            s.sendall((json.dumps(payload) + "\n").encode("utf-8"))
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
        if not buf:
            raise ConnectionError("control coordinator closed the "
                                  "connection without a reply")
        return json.loads(buf.decode("utf-8"))


class LocalTransport:
    """In-process transport driving a :class:`LeaseState` directly —
    no sockets, no threads, fake-clock friendly. Blocking ops return
    ``wait`` decisions instead of blocking; :class:`WorkerAgent`
    polls, so agent behavior is identical over both transports."""

    def __init__(self, state: LeaseState):
        self.state = state

    def request(self, payload: dict,
                timeout_s: Optional[float] = None) -> dict:
        op = payload.get("op")
        st = self.state
        if op == "join":
            mid = st.join(payload.get("member"))
            g = st.grant_for(mid)
            if g is None:
                return {"ok": True, "decision": "wait", "member": mid,
                        "epoch": st.epoch}
            g.setdefault("member", mid)
            return g
        if op == "grant":
            g = st.grant_for(int(payload["member"]))
            if g is None:
                return {"ok": True, "decision": "wait",
                        "member": int(payload["member"]),
                        "epoch": st.epoch}
            return g
        if op == "renew":
            return st.renew(int(payload["member"]),
                            int(payload["epoch"]))
        if op == "barrier":
            return st.arrive(int(payload["member"]),
                             int(payload["epoch"]),
                             int(payload["step"]))
        if op == "sync":
            return st.sync(int(payload["member"]),
                           int(payload["epoch"]),
                           str(payload["key"]),
                           payload.get("payload"))
        if op == "leave":
            return st.leave(int(payload["member"]))
        if op == "info":
            return st.info()
        return {"ok": False, "error": "bad_op"}


class WorkerAgent:
    """One per training process: joins the coordinator, renews its
    lease from a background thread (rank-seeded jitter so a fleet's
    renewals decorrelate), arrives at step barriers, and converts
    protocol outcomes into the exceptions/plans the fit driver acts
    on — ``stale_epoch`` becomes a :class:`RecoveryPlan`, ``fenced``
    a :class:`HostFencedException`, and retry exhaustion against the
    transport a :class:`CoordinatorLostException`."""

    def __init__(self, transport, *, rank_hint: Optional[int] = None,
                 policy: Optional[RetryPolicy] = None,
                 renew_jitter: float = 0.2, poll_s: float = 0.05,
                 barrier_timeout_s: float = 120.0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 registry=None):
        if isinstance(transport, str):
            transport = TcpTransport(transport)
        self.transport = transport
        self.rank_hint = rank_hint
        self.policy = policy or RetryPolicy(
            max_attempts=4, base_delay=0.25, max_delay=2.0,
            total_timeout=15.0,
            seed=rank_hint if rank_hint is not None else 0,
        )
        self.poll_s = float(poll_s)
        self.barrier_timeout_s = float(barrier_timeout_s)
        self.clock = clock
        self.sleep = sleep
        self.member: Optional[int] = None
        self.epoch = 0
        self.rank: Optional[int] = None
        self.num: Optional[int] = None
        self.jax_coordinator: Optional[str] = None
        self.lease_s: Optional[float] = None
        self._jitter = float(renew_jitter)
        self._rng = random.Random(
            rank_hint if rank_hint is not None else 0)
        self._lock = threading.Lock()
        self._plan: Optional[RecoveryPlan] = None
        self._fenced = False
        self._lost = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        registry = registry if registry is not None else _default_registry()
        self._m_rtt = registry.summary(
            "control_rtt_ms",
            help="control-plane request round-trip latency (ms)",
        )._default()

    # -- wire ------------------------------------------------------------

    def _call(self, payload: dict,
              timeout_s: Optional[float] = None) -> dict:
        t0 = self.clock()
        try:
            resp = retry_call(self.transport.request, payload,
                              timeout_s=timeout_s, policy=self.policy)
        except (RetryExhaustedException,
                DeadlineExceededException) as e:
            with self._lock:
                self._lost = True
            raise CoordinatorLostException(
                "control coordinator unreachable "
                f"(op={payload.get('op')!r}, member={self.member})"
            ) from e
        self._m_rtt.observe((self.clock() - t0) * 1000.0)
        if resp.get("error") == "fenced":
            with self._lock:
                self._fenced = True
            raise HostFencedException(
                f"member {self.member} fenced at epoch "
                f"{resp.get('epoch')}: a zombie must not touch the "
                "mesh (rejoin as a fresh member)"
            )
        return resp

    def _stash_plan(self, resp: dict) -> Optional[RecoveryPlan]:
        """Stash a stale-epoch plan for the fit loop — but only when
        it is NEWER than the epoch this agent already adopted. A
        late-arriving response from a pre-recovery request (the
        renewal thread racing the barrier) must not re-trigger the
        same recovery."""
        plan = RecoveryPlan.from_dict(resp["plan"])
        with self._lock:
            if plan.epoch <= self.epoch:
                return None
            self._plan = plan
        return plan

    # -- membership ------------------------------------------------------

    def join(self, timeout_s: float = 60.0) -> RecoveryPlan:
        """Join and block until the mesh forms (or this member is
        admitted at an epoch bump). Returns the initial grant."""
        deadline = self.clock() + timeout_s
        resp = self._call({"op": "join", "member": self.rank_hint,
                           "timeout_s": timeout_s},
                          timeout_s=timeout_s + 10.0)
        while resp.get("decision") == "wait":
            self.member = int(resp.get("member", -1))
            if self.clock() >= deadline:
                raise CoordinatorLostException(
                    f"mesh never formed within {timeout_s}s "
                    f"(member={self.member})")
            self.sleep(self.poll_s)
            resp = self._call({"op": "grant", "member": self.member})
        if resp.get("error") == "join_timeout":
            raise CoordinatorLostException(
                f"mesh never formed within {timeout_s}s "
                f"(member={resp.get('member')})")
        plan = RecoveryPlan.from_dict(resp)
        self.adopt(plan)
        logger.info(
            "control plane: joined as member %d rank %d/%d epoch %d",
            plan.member, plan.rank, plan.num, plan.epoch)
        return plan

    def adopt(self, plan: RecoveryPlan) -> None:
        """Make ``plan`` this agent's current epoch. Called BEFORE the
        jax runtime re-forms, so background renewals carry the new
        epoch and keep the lease alive through a slow re-init."""
        member = plan.member if plan.member is not None else self.member
        with self._lock:
            self.member = member
            self.epoch = plan.epoch
            self.rank = (plan.rank if plan.rank is not None
                         else plan.members.index(member))
            self.num = plan.num
            self.jax_coordinator = plan.jax_coordinator
            self.lease_s = plan.lease_s
            self._plan = None

    def renew(self) -> Optional[RecoveryPlan]:
        """One lease renewal. Returns a plan when the epoch moved."""
        resp = self._call({"op": "renew", "member": self.member,
                           "epoch": self.epoch})
        if resp.get("error") == "stale_epoch":
            return self._stash_plan(resp)
        return None

    def leave(self) -> None:
        self._call({"op": "leave", "member": self.member})

    # -- the renewal thread ---------------------------------------------

    def next_interval(self) -> float:
        """Renewal interval: a third of the lease, jittered by a
        rank-seeded rng (the ``ServingRouter.health_jitter`` pattern)
        so a fleet's renewals don't synchronize into bursts."""
        base = (self.lease_s or 2.0) / 3.0
        return base * (1.0 + self._jitter * (2.0 * self._rng.random()
                                             - 1.0))

    def start_renewals(self) -> None:
        if self._thread is not None:
            return

        def _loop():
            while not self._stop.wait(self.next_interval()):
                try:
                    # a newer plan gets stashed for the next barrier;
                    # keep renewing regardless — stale-epoch renewals
                    # still extend the lease, keeping this host alive
                    # through a slow recovery
                    self.renew()
                except (CoordinatorLostException,
                        HostFencedException):
                    return  # verdict stashed; surfaced at the barrier
                except Exception as e:
                    logger.warning(
                        "control plane: renewal hiccup: %r", e)

        self._thread = threading.Thread(
            target=_loop, name="lease-renewals", daemon=True)
        self._thread.start()

    def stop_renewals(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._thread = None
        self._stop = threading.Event()

    # -- step boundary ---------------------------------------------------

    def pending_plan(self) -> Optional[RecoveryPlan]:
        with self._lock:
            return self._plan

    def raise_verdicts(self) -> None:
        """Surface a terminal verdict reached by the renewal thread."""
        with self._lock:
            fenced, lost = self._fenced, self._lost
        if fenced:
            raise HostFencedException(
                f"member {self.member} fenced at epoch {self.epoch}")
        if lost:
            raise CoordinatorLostException(
                "control coordinator unreachable (renewal thread "
                "exhausted its retries)")

    def step_barrier(self, step: int,
                     timeout_s: Optional[float] = None
                     ) -> Optional[RecoveryPlan]:
        """Arrive at the step barrier; block until every member has.
        Returns ``None`` to proceed, or a :class:`RecoveryPlan` when
        the epoch moved (host died / member admitted) — the caller
        runs recovery, then :meth:`adopt` makes the plan current."""
        self.raise_verdicts()
        plan = self.pending_plan()
        if plan is not None:
            return plan
        timeout_s = (self.barrier_timeout_s if timeout_s is None
                     else float(timeout_s))
        deadline = self.clock() + timeout_s
        while True:
            resp = self._call(
                {"op": "barrier", "member": self.member,
                 "epoch": self.epoch, "step": int(step),
                 "timeout_s": timeout_s},
                timeout_s=timeout_s + 10.0)
            if resp.get("decision") == "wait":
                if self.clock() >= deadline:
                    raise ControlPlaneException(
                        f"step barrier {step} timed out after "
                        f"{timeout_s}s (epoch {self.epoch}): peers "
                        "wedged but not declared dead")
                self.sleep(self.poll_s)
                continue
            if resp.get("error") == "stale_epoch":
                plan = self._stash_plan(resp)
                if plan is not None:
                    return plan
                continue  # epoch already adopted: re-arrive under it
            if resp.get("error") == "barrier_timeout":
                raise ControlPlaneException(
                    f"step barrier {step} timed out after {timeout_s}s "
                    f"(epoch {self.epoch}): peers wedged but not "
                    "declared dead")
            return None

    def sync_barrier(self, key: str, payload=None,
                     timeout_s: Optional[float] = None
                     ) -> Optional[Dict[int, object]]:
        """Payload-carrying named barrier — the checkpoint commit
        fence. Blocks until every member of the current epoch arrives
        with its payload, then returns ``{member_id: payload}`` for
        all of them. Returns ``None`` when the epoch moved underneath
        (a member died or was admitted): the caller's commit MUST
        abort — the membership its shards were written under no
        longer exists. Safe from any thread (each request rides a
        fresh connection), which is the point: write-behind
        checkpoint writers commit here without touching the training
        thread's step barriers."""
        self.raise_verdicts()
        timeout_s = (self.barrier_timeout_s if timeout_s is None
                     else float(timeout_s))
        deadline = self.clock() + timeout_s
        while True:
            resp = self._call(
                {"op": "sync", "member": self.member,
                 "epoch": self.epoch, "key": str(key),
                 "payload": payload, "timeout_s": timeout_s},
                timeout_s=timeout_s + 10.0)
            if resp.get("decision") == "wait":
                if self.clock() >= deadline:
                    raise ControlPlaneException(
                        f"sync barrier {key!r} timed out after "
                        f"{timeout_s}s (epoch {self.epoch}): peers "
                        "wedged but not declared dead")
                self.sleep(self.poll_s)
                continue
            if resp.get("error") == "stale_epoch":
                self._stash_plan(resp)
                return None
            if resp.get("error") == "barrier_timeout":
                raise ControlPlaneException(
                    f"sync barrier {key!r} timed out after "
                    f"{timeout_s}s (epoch {self.epoch}): peers wedged "
                    "but not declared dead")
            return {int(m): p
                    for m, p in resp.get("payloads", {}).items()}

    def close(self, leave: bool = False) -> None:
        """Stop renewing; optionally a graceful ``leave`` (off by
        default — at normal end-of-fit every member finishes the same
        final barrier, so departing silently avoids a pointless
        tail of epoch bumps)."""
        self.stop_renewals()
        if leave and self.member is not None:
            try:
                self.leave()
            except ControlPlaneException:
                pass


# -- fit-driver hook (the preemption._active pattern) --------------------

_active_agent: Optional[WorkerAgent] = None


def install_agent(agent: WorkerAgent) -> WorkerAgent:
    """Make ``agent`` the process-wide control-plane agent the fit
    drivers consult (``check_fit``). One per process, like the
    preemption handler."""
    global _active_agent
    _active_agent = agent
    return agent


def uninstall_agent(agent: Optional[WorkerAgent] = None) -> None:
    global _active_agent
    if agent is None or _active_agent is agent:
        _active_agent = None


def active_agent() -> Optional[WorkerAgent]:
    return _active_agent


def check_fit(model=None) -> None:
    """Fast-path hook for the single-process fit drivers: surface a
    fence/coordinator-loss verdict reached by the renewal thread
    between barriers. No-op (one attribute read + branch) when no
    agent is installed."""
    agent = _active_agent
    if agent is None:
        return
    agent.raise_verdicts()
