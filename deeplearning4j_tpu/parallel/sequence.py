"""Sequence/context parallelism: ring attention over a ``seq`` mesh
axis.

Net-new capability vs the reference (which predates attention — its
only long-sequence tools are truncated BPTT and masking, SURVEY.md
§5), but first-class here: sequences too long for one chip's HBM are
sharded along time across the mesh, and attention runs blockwise with
an online-softmax accumulator while K/V blocks rotate around the ring
via ``lax.ppermute`` — each hop rides ICI, overlapping with the local
block's compute (the RingAttention / blockwise-parallel-transformer
scheme).

Use ``ring_self_attention`` inside ``shard_map`` over a mesh with a
``seq`` axis; time-sharded q/k/v stay resident, only one K/V block is
in flight per step, so memory is O(t_local) instead of O(t), and the
score matrix never materializes beyond [t_local, t_local] tiles —
XLA tiles those onto the MXU."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG = -1e9  # masked-score fill; exp(_NEG - m) underflows to exactly 0


from deeplearning4j_tpu.parallel.compat import shard_map_compat as _shard_map


def attention(q, k, v, causal: bool = False, mask=None):
    """Plain (single-shard) scaled-dot-product attention on
    [b, h, t, d] — the reference semantics ring_attention must match;
    XLA fuses softmax into the two matmuls."""
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    t = q.shape[2]
    if causal:
        cm = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(cm[None, None], s, _NEG)
    if mask is not None:
        # mask: [b, t] validity of keys
        s = jnp.where(mask[:, None, None, :] > 0, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def ring_attention(q, k, v, axis_name: str, axis_size: int,
                   causal: bool = False, mask=None):
    """Blockwise ring attention. Call inside ``shard_map`` with q/k/v
    (and mask) sharded on their time axis over ``axis_name``:
    q/k/v [b, h, t_local, d], mask [b, t_local] or None.

    Per ring step every device holds one K/V block, computes its
    [t_local, t_local] score tile, folds it into the online-softmax
    accumulator (m running max, l running denominator, o running
    numerator), and forwards the block to the next device with
    ``ppermute`` — after ``axis_size`` hops each query has seen every
    key, and the result equals single-device softmax attention. The
    whole loop is a ``lax.scan``, so it jits once and autodiff gives
    the ring backward pass (a reverse rotation) for free."""
    tl = q.shape[2]
    my = jax.lax.axis_index(axis_name)
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    q_pos = my * tl + jnp.arange(tl)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(carry, i):
        o, l, m, k_cur, v_cur, mask_cur = carry
        src = (my - i) % axis_size
        k_pos = src * tl + jnp.arange(tl)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur) * scale
        if causal:
            cm = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(cm[None, None], s, _NEG)
        if mask_cur is not None:
            s = jnp.where(mask_cur[:, None, None, :] > 0, s, _NEG)
        m_blk = jnp.max(s, axis=-1, keepdims=True)       # [b,h,tl,1]
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v_cur)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = (
            jax.lax.ppermute(mask_cur, axis_name, perm)
            if mask_cur is not None else None
        )
        return (o_new, l_new, m_new, k_nxt, v_nxt, mask_nxt), None

    o0 = jnp.zeros_like(q)
    l0 = jnp.zeros(q.shape[:3] + (1,), q.dtype)
    # start far below any real score so the first correction is 0
    m0 = jnp.full(q.shape[:3] + (1,), 2.0 * _NEG, q.dtype)
    (o, l, _, _, _, _), _ = jax.lax.scan(
        step, (o0, l0, m0, k, v, mask), jnp.arange(axis_size)
    )
    return o / jnp.maximum(l, 1e-20)


def ring_self_attention_sharded(mesh: Mesh, q, k, v,
                                causal: bool = False, mask=None,
                                seq_axis: str = "seq"):
    """Convenience wrapper: shard [b, h, t, d] q/k/v on the time axis
    over ``mesh[seq_axis]`` and run ring attention; returns the
    gathered [b, h, t, d] result. For full control (e.g. keeping
    activations sharded through a whole transformer block), call
    ``ring_attention`` inside your own ``shard_map``."""
    shard_map = _shard_map()

    axis_size = mesh.shape[seq_axis]
    qkv_spec = P(None, None, seq_axis, None)
    mask_spec = P(None, seq_axis)

    if mask is None:
        fn = shard_map(
            functools.partial(
                ring_attention, axis_name=seq_axis,
                axis_size=axis_size, causal=causal, mask=None,
            ),
            mesh=mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec, check_rep=False,
        )
        return fn(q, k, v)

    def body(q_, k_, v_, mask_):
        return ring_attention(
            q_, k_, v_, axis_name=seq_axis, axis_size=axis_size,
            causal=causal, mask=mask_,
        )

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec, check_rep=False,
    )
    return fn(q, k, v, mask)


def build_seq_mesh(data: int = 1, seq: Optional[int] = None,
                   devices=None) -> Mesh:
    """(data, seq) mesh for context parallelism; defaults to all
    devices on ``seq``."""
    import numpy as np

    devices = list(devices if devices is not None else jax.devices())
    if seq is None:
        if len(devices) % data != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible by data={data}"
            )
        seq = len(devices) // data
    if data * seq > len(devices):
        raise ValueError(
            f"data({data}) x seq({seq}) > {len(devices)} devices"
        )
    devices = devices[:data * seq]
    n = len(devices)
    return Mesh(
        np.asarray(devices).reshape(data, seq), axis_names=("data", "seq")
    )
