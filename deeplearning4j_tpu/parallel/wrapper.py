"""ParallelWrapper — faithful parameter-averaging semantics (reference:
``parallelism/ParallelWrapper.java:37,:138-177`` single-node and
``spark/impl/paramavg/ParameterAveragingTrainingMaster.java:74``
cluster-scale; both are the same algorithm: N model replicas each fit
``averaging_frequency`` minibatches from their own data shard, then
parameters (and optionally updater state, ``:168-177``) are averaged
and redistributed).

TPU-native realization: replicas live as a stacked leading axis on
every param (``[workers, ...]``), sharded over the mesh's ``data``
axis — one replica per device group. The per-replica fit step is a
``vmap`` of the single-model step (one compiled program, all replicas
stepping in parallel on their own chips), and the averaging round is a
``mean`` over the replica axis — which XLA lowers to the same
all-reduce the reference performs via ``Nd4j.averageAndPropagate`` /
RDD aggregate, but over ICI.

Kept alongside ``DistributedTrainer`` (per-step gradient all-reduce)
to reproduce reference trajectories exactly — the equivalence test
``TestCompareParameterAveragingSparkVsSingleMachine`` has a direct
analog here (see tests/test_parallel.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import build_mesh


class ParallelWrapper:
    def __init__(self, model, workers: int = 2,
                 averaging_frequency: int = 1,
                 average_updaters: bool = True,
                 prefetch_buffer: int = 2,
                 mesh: Optional[Mesh] = None,
                 report_score_after_averaging: bool = True):
        self.model = model
        self.workers = workers
        self.averaging_frequency = max(int(averaging_frequency), 1)
        self.average_updaters = average_updaters
        self.prefetch_buffer = prefetch_buffer
        self.mesh = mesh
        if model.params is None:
            model.init()
        self._replica_params = None
        self._replica_upd = None
        self._replica_state = None
        self._jit_replica_step = None
        self._jit_average = None
        self._steps_since_avg = 0

    # -- replica plumbing ----------------------------------------------

    def _stack(self, tree):
        return jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(
                a[None], (self.workers,) + a.shape
            ).copy() if hasattr(a, "shape") else a,
            tree,
        )

    def _shard_replicas(self, tree):
        if self.mesh is None:
            return tree
        sh = NamedSharding(self.mesh, P("data"))
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sh), tree
        )

    def _ensure_replicas(self) -> None:
        if self._replica_params is None:
            self._replica_params = self._shard_replicas(
                self._stack(self.model.params)
            )
            self._replica_upd = self._shard_replicas(
                self._stack(self.model.updater_state)
            )
            self._replica_state = self._shard_replicas(
                self._stack(self.model.state)
            )

    def _build_replica_step(self):
        m = self.model
        updater = m.updater_def
        # MLN and CG share positional (params, state, x, y, mask, rng)
        # in _score_pure; only the features-mask keyword differs
        fmask_kw = "fmasks" if self._is_graph() else "fmask"

        def one(params, upd_state, state, x, y, lm, fm, lrs, t, rng):
            def loss_fn(p):
                s, new_state = m._score_pure(
                    p, state, x, y, lm, rng, train=True,
                    **{fmask_kw: fm},
                )
                return s, new_state

            (score, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            new_params, new_upd = updater.update(
                grads, upd_state, params, lrs, t
            )
            return new_params, new_upd, new_state, score

        vstep = jax.vmap(
            one, in_axes=(0, 0, 0, 0, 0, 0, 0, None, None, 0),
            out_axes=(0, 0, 0, 0),
        )
        return jax.jit(vstep, donate_argnums=(0, 1, 2))

    def _is_graph(self) -> bool:
        return not hasattr(self.model, "layer_names")

    def _build_average(self):
        def avg(replica_tree):
            return jax.tree_util.tree_map(
                lambda a: jnp.mean(a, axis=0), replica_tree
            )
        return jax.jit(avg)

    # -- public API -----------------------------------------------------

    def fit(self, iterator, epochs: int = 1) -> None:
        """Each averaging round consumes ``workers`` minibatches — one
        per replica (reference: MagicQueue distributing batches across
        device queues). Prefetch rides the shared training input
        pipeline (``datasets.prefetch.PrefetchIterator``): host
        materialization overlaps the replica rounds, worker-thread
        faults surface as ``DL4JFaultException``, and the queue-depth
        / prefetch-wait signals land in the metrics registry."""
        from deeplearning4j_tpu.datasets.prefetch import (
            PrefetchIterator,
        )

        m = self.model
        self._ensure_replicas()
        if self._jit_replica_step is None:
            self._jit_replica_step = self._build_replica_step()
            self._jit_average = self._build_average()
        dtype = jnp.dtype(m.conf.dtype)
        owned_prefetch = None
        source = iterator
        if self.prefetch_buffer > 0 and hasattr(iterator, "has_next"):
            source = owned_prefetch = PrefetchIterator(
                iterator, queue_depth=self.prefetch_buffer,
            )
        try:
            for _ in range(epochs):
                buf = []
                for ds in iter(source):
                    buf.append(ds)
                    if len(buf) == self.workers:
                        self._round(buf, dtype)
                        buf = []
                # trailing partial round: recycle batches to fill
                # workers
                if buf:
                    orig = len(buf)
                    while len(buf) < self.workers:
                        buf.append(buf[len(buf) % orig])
                    self._round(buf, dtype)
                if hasattr(source, "reset"):
                    source.reset()
                m.epoch_count += 1
        finally:
            if owned_prefetch is not None:
                owned_prefetch.shutdown()
        self._sync_model()

    def _stack_batches(self, batches, get, dtype):
        """Stack one field replica-wise. For a ComputationGraph model
        every field is a LIST of per-slot arrays (bare DataSet arrays
        are wrapped), and each slot stacks separately — the vmapped
        step maps over the list pytree. ``None`` fields/slots stay
        None."""
        graph = self._is_graph()

        def field(b):
            v = get(b)
            if graph and v is not None and not isinstance(
                v, (list, tuple)
            ):
                return [v]
            return v

        def mixed_error():
            raise ValueError(
                "replicas in one averaging round mix masked and "
                "unmasked batches (or mask different slots); group "
                "them — an absent mask means all timesteps count, so "
                "pass explicit ones to mix"
            )

        values = [field(b) for b in batches]
        if any(v is None for v in values):
            if any(v is not None for v in values):
                mixed_error()
            return None
        if isinstance(values[0], (list, tuple)):
            out = []
            for i in range(len(values[0])):
                slot = [v[i] for v in values]
                if any(a is None for a in slot):
                    if any(a is not None for a in slot):
                        mixed_error()
                    out.append(None)
                else:
                    out.append(jnp.stack([
                        jnp.asarray(a, dtype) for a in slot
                    ]))
            return out
        return jnp.stack([jnp.asarray(v, dtype) for v in values])

    @staticmethod
    def _mask_of(b, *names):
        for n in names:
            v = getattr(b, n, None)
            if v is not None:
                return v
        return None

    def _round(self, batches, dtype) -> None:
        m = self.model
        x = self._stack_batches(batches, lambda b: b.features, dtype)
        y = self._stack_batches(batches, lambda b: b.labels, dtype)
        lm = self._stack_batches(
            batches,
            lambda b: self._mask_of(b, "labels_masks", "labels_mask"),
            dtype,
        )
        fm = self._stack_batches(
            batches,
            lambda b: self._mask_of(b, "features_masks", "features_mask"),
            dtype,
        )
        lrs = m.updater_def.scheduled_lrs(m.iteration_count)
        t = jnp.asarray(m.iteration_count + 1, jnp.float32)
        rngs = jax.vmap(
            lambda i: jax.random.fold_in(
                jax.random.fold_in(m._base_key, m.iteration_count), i
            )
        )(jnp.arange(self.workers))
        (
            self._replica_params, self._replica_upd, self._replica_state,
            scores,
        ) = self._jit_replica_step(
            self._replica_params, self._replica_upd, self._replica_state,
            x, y, lm, fm,
            {k: jnp.asarray(v, jnp.float32) for k, v in lrs.items()},
            t, rngs,
        )
        m.iteration_count += 1
        self._reset_recurrent_replica_state()
        self._steps_since_avg += 1
        if self._steps_since_avg >= self.averaging_frequency:
            self._average()
        m.score_value = jnp.mean(scores)  # lazy; reading syncs
        for listener in m.listeners:
            listener.iteration_done(m, m.iteration_count)

    def _reset_recurrent_replica_state(self) -> None:
        """Recurrent carry doesn't persist across minibatches (matches
        the single-model fit path); also keeps the replica-state pytree
        structure stable so the vmapped step never recompiles."""
        m = self.model
        if hasattr(m, "layer_names"):
            pairs = list(zip(m.layer_names, m.conf.layers))
        else:
            pairs = [
                (n, m.conf.vertices[n].layer_conf)
                for n in m.layer_vertex_names
            ]
        for name, layer in pairs:
            if layer.is_recurrent():
                self._replica_state[name] = {}

    def _average(self) -> None:
        """The averaging round (reference ``Nd4j.averageAndPropagate``;
        updater-state averaging per ``ParallelWrapper.java:168-177``).
        Layer state (BN running stats) averages too — in the reference
        those are parameters, so parameter averaging covers them."""
        avg_params = self._jit_average(self._replica_params)
        self._replica_params = self._shard_replicas(
            self._stack(avg_params)
        )
        if self.average_updaters:
            avg_upd = self._jit_average(self._replica_upd)
            self._replica_upd = self._shard_replicas(self._stack(avg_upd))
        avg_state = self._jit_average(self._replica_state)
        self._replica_state = self._shard_replicas(self._stack(avg_state))
        self._steps_since_avg = 0

    def _sync_model(self) -> None:
        """Fold averaged replicas back into the wrapped model
        (reference: master model updated after averaging)."""
        if self._replica_params is None:
            return
        if self._steps_since_avg:
            self._average()
        self.model.params = jax.tree_util.tree_map(
            lambda a: a[0], self._replica_params
        )
        self.model.updater_state = jax.tree_util.tree_map(
            lambda a: a[0], self._replica_upd
        )
        self.model.state = jax.tree_util.tree_map(
            lambda a: a[0], self._replica_state
        )
        self._replica_params = None
        self._replica_upd = None
        self._replica_state = None
