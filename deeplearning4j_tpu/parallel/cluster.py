"""Cluster-scale training SPI (reference ``dl4j-spark``:
``SparkDl4jMultiLayer.java:77``, ``TrainingMaster`` SPI
``spark/api/TrainingMaster.java:29``, ``TrainingWorker``
``spark/api/TrainingWorker.java:21``,
``ParameterAveragingTrainingMaster.java:74`` and its split sizing
``:319-330``, export-based training
``spark/data/BatchAndExportDataSetsFunction.java``, distributed eval
``spark/impl/multilayer/evaluation/EvaluateFlatMapFunction.java:41``,
phase stats ``ParameterAveragingTrainingMasterStats.java``).

TPU-native realization: where Spark broadcasts params to executors and
aggregates them back over the shuffle network, here the "cluster" is
the device mesh — replicas are a stacked+sharded leading axis stepped
by one vmapped XLA program (``ParallelWrapper``) and the averaging
round is an on-device mean over ICI. The Spark-side SPI shape
(master/worker split, averaging frequency, splits over the dataset,
per-phase stats) is preserved so reference users find the same
control knobs; multi-host scale-out over DCN is
``deeplearning4j_tpu.parallel.mesh.init_distributed``.
"""

from __future__ import annotations

import glob
import os
import time
from typing import Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


# ---------------------------------------------------------------------------
# SPI
# ---------------------------------------------------------------------------


class TrainingWorker:
    """Worker-side SPI (reference ``TrainingWorker.java:21``): how one
    executor steps a model replica. The vmapped replica step plays
    this role on-mesh; the class exists as the extension seam for
    custom worker logic (hooks, stats)."""

    def get_initial_model(self, master: "TrainingMaster"):
        return master.model

    def process_minibatch(self, ds: DataSet, model, is_last: bool):
        raise NotImplementedError

    def get_final_result(self, model):
        raise NotImplementedError


class TrainingHook:
    """Pre/post-update hook SPI (reference
    ``spark/api/TrainingHook.java`` — the parameter-server module stubs
    this; kept for the same extension point)."""

    def pre_update(self, ds: DataSet, model) -> None:
        pass

    def post_update(self, ds: DataSet, model) -> None:
        pass


class TrainingMaster:
    """Master-side SPI (reference ``TrainingMaster.java:29``)."""

    def execute_training(self, net, data) -> None:
        raise NotImplementedError

    def get_training_stats(self):
        return None


# ---------------------------------------------------------------------------
# Stats
# ---------------------------------------------------------------------------


class ParameterAveragingTrainingMasterStats:
    """Per-phase wall-clock timing (reference
    ``ParameterAveragingTrainingMasterStats.java`` — logFitStart/
    logSplitStart/logAggregateStartTime bracketing)."""

    def __init__(self):
        self.fit_times_ms: List[float] = []
        self.split_times_ms: List[float] = []
        self.aggregate_times_ms: List[float] = []

    def as_dict(self) -> dict:
        def stats(v):
            return {
                "count": len(v),
                "total_ms": float(np.sum(v)) if v else 0.0,
                "mean_ms": float(np.mean(v)) if v else 0.0,
            }
        return {
            "fit": stats(self.fit_times_ms),
            "split": stats(self.split_times_ms),
            "aggregate": stats(self.aggregate_times_ms),
        }


class _Timer:
    def __init__(self, sink: List[float]):
        self.sink = sink

    def __enter__(self):
        self.t0 = time.perf_counter()

    def __exit__(self, *exc):
        self.sink.append((time.perf_counter() - self.t0) * 1000.0)


# ---------------------------------------------------------------------------
# ParameterAveragingTrainingMaster
# ---------------------------------------------------------------------------


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Synchronous periodic parameter averaging (reference
    ``ParameterAveragingTrainingMaster.java``). Splits the dataset
    into splits of ``workers * batch_size * averaging_frequency``
    examples (``getNumDataSetObjectsPerSplit`` math ``:319-330``),
    each split trains ``averaging_frequency`` minibatches per worker
    and averages params (+ updater state per ``saveUpdater``)."""

    def __init__(self, workers: int = 2, batch_size_per_worker: int = 16,
                 averaging_frequency: int = 1, save_updater: bool = True,
                 prefetch_num_batches: int = 2,
                 collect_training_stats: bool = False,
                 mesh=None):
        self.workers = workers
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = max(int(averaging_frequency), 1)
        self.save_updater = save_updater
        self.prefetch_num_batches = prefetch_num_batches
        self.collect_training_stats = collect_training_stats
        self.mesh = mesh
        self.stats = (
            ParameterAveragingTrainingMasterStats()
            if collect_training_stats else None
        )
        self.model = None

    class Builder:
        """Reference ``ParameterAveragingTrainingMaster.Builder``."""

        def __init__(self, workers_or_examples: int = 2):
            self._workers = workers_or_examples
            self._batch = 16
            self._avg = 1
            self._save_updater = True
            self._prefetch = 2
            self._stats = False
            self._mesh = None

        def batch_size_per_worker(self, n):
            self._batch = n; return self

        def averaging_frequency(self, n): self._avg = n; return self
        def save_updater(self, b): self._save_updater = b; return self
        def worker_prefetch_num_batches(self, n):
            self._prefetch = n; return self

        def collect_training_stats(self, b): self._stats = b; return self
        def mesh(self, m): self._mesh = m; return self

        def build(self) -> "ParameterAveragingTrainingMaster":
            return ParameterAveragingTrainingMaster(
                workers=self._workers, batch_size_per_worker=self._batch,
                averaging_frequency=self._avg,
                save_updater=self._save_updater,
                prefetch_num_batches=self._prefetch,
                collect_training_stats=self._stats, mesh=self._mesh,
            )

    # -- split plumbing --------------------------------------------------

    def num_examples_per_split(self) -> int:
        """Reference ``getNumDataSetObjectsPerSplit``: one split feeds
        every worker ``averaging_frequency`` batches."""
        return (
            self.workers * self.batch_size_per_worker
            * self.averaging_frequency
        )

    def _batches_of(self, ds: DataSet):
        """Slice one big DataSet into worker minibatches, masks
        included; the tail remainder becomes a final smaller batch
        (nothing is silently dropped)."""
        b = self.batch_size_per_worker
        n = ds.num_examples()

        def cut(a, i):
            return None if a is None else np.asarray(a)[i:i + b]

        return [
            DataSet(
                features=cut(ds.features, i), labels=cut(ds.labels, i),
                features_mask=cut(ds.features_mask, i),
                labels_mask=cut(ds.labels_mask, i),
            )
            for i in range(0, n, b)
        ]

    def _batches_of_multi(self, mds):
        """Slice one big MultiDataSet into worker minibatches, every
        input/label/mask slot included."""
        from deeplearning4j_tpu.datasets.api import MultiDataSet

        b = self.batch_size_per_worker
        n = mds.num_examples()

        def cuts(group, i):
            if group is None:
                return None
            return [
                None if a is None else np.asarray(a)[i:i + b]
                for a in group
            ]

        return [
            MultiDataSet(
                features=cuts(mds.features, i),
                labels=cuts(mds.labels, i),
                features_masks=cuts(mds.features_masks, i),
                labels_masks=cuts(mds.labels_masks, i),
            )
            for i in range(0, n, b)
        ]

    # -- TrainingMaster --------------------------------------------------

    def execute_training(self, net, data) -> None:
        """``data``: a DataSetIterator, an iterable of DataSets, or one
        big DataSet (the RDD analog). Batches are dealt round-robin to
        workers (the balanced-repartition step,
        ``SparkUtils.repartition``), each averaging round consumes
        ``workers × averaging_frequency`` of them."""
        self.model = net
        wrapper = ParallelWrapper(
            net, workers=self.workers,
            averaging_frequency=self.averaging_frequency,
            average_updaters=self.save_updater,
            prefetch_buffer=self.prefetch_num_batches,
            mesh=self.mesh,
        )
        batches = self._as_batches(data)
        timer = (
            _Timer(self.stats.fit_times_ms) if self.stats
            else _nulltimer
        )
        # replicas step as one stacked vmap, so every batch in a round
        # must share a shape AND mask presence: group by (size, which
        # masks exist) — iterator input can carry several distinct
        # off-sizes plus masked/unmasked mixes — and fit once per
        # uniform group, full-size groups first.
        def mask_sig(b):
            def sig_of(group, single):
                g = getattr(b, group, None)
                if g is not None:
                    return tuple(m is not None for m in g)  # per slot
                return (getattr(b, single, None) is not None,)

            return (
                sig_of("features_masks", "features_mask"),
                sig_of("labels_masks", "labels_mask"),
            )

        by_size: dict = {}
        for b in batches:
            by_size.setdefault(
                (b.num_examples(), mask_sig(b)), []
            ).append(b)
        ordered = sorted(
            by_size.items(),
            key=lambda kv: (kv[0][0] != self.batch_size_per_worker,
                            kv[0]),
        )
        with timer:
            for _, group in ordered:
                wrapper.fit(_ListIterator(group))

    def _as_batches(self, data) -> List[DataSet]:
        timer = (
            _Timer(self.stats.split_times_ms) if self.stats
            else _nulltimer
        )
        from deeplearning4j_tpu.datasets.api import MultiDataSet

        with timer:
            if isinstance(data, DataSet):
                return self._batches_of(data)
            if isinstance(data, MultiDataSet):
                return self._batches_of_multi(data)
            return list(iter(data))

    def get_training_stats(self):
        return self.stats


class _NullTimer:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return None


_nulltimer = _NullTimer()


class _ListIterator(DataSetIterator):
    def __init__(self, batches: List[DataSet]):
        self._batches = batches
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._batches)

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        b = self._batches[self._pos]
        self._pos += 1
        return b

    def reset(self) -> None:
        self._pos = 0


# ---------------------------------------------------------------------------
# SparkDl4jMultiLayer analog
# ---------------------------------------------------------------------------


class _ClusterModelFacade:
    """Shared driver-side facade plumbing: fit over in-memory data
    (``fit(JavaRDD)`` analog), fit over exported batch files
    (``fitPaths``), sharded evaluation (per-shard delegation to the
    engine's own ``evaluate`` + ``Evaluation.merge`` — reference
    ``EvaluateFlatMapFunction.java:41`` + ``EvaluationReduceFunction``),
    scoring."""

    def __init__(self, net, training_master: TrainingMaster):
        self.net = net
        self.training_master = training_master

    def fit(self, data) -> None:
        self.training_master.execute_training(self.net, data)

    def fit_paths(self, paths: Iterable[str]) -> None:
        """Train from exported minibatch files (reference export-based
        path ``fitPaths:265``)."""
        self.training_master.execute_training(
            self.net, PathDataSetIterator(list(paths))
        )

    def evaluate(self, data, num_shards: Optional[int] = None):
        from deeplearning4j_tpu.eval import Evaluation

        batches = (
            data if isinstance(data, list) else list(iter(data))
        )
        n = num_shards or getattr(self.training_master, "workers", 1)
        shards: List[list] = [[] for _ in range(max(n, 1))]
        for i, b in enumerate(batches):
            shards[i % len(shards)].append(b)
        merged: Optional[Evaluation] = None
        for shard in shards:
            if not shard:
                continue
            e = self.net.evaluate(iter(shard))
            merged = e if merged is None else merged.merge(e)
        return merged if merged is not None else Evaluation()

    def get_score(self, ds) -> float:
        return float(self.net.score(ds))


class ClusterDl4jMultiLayer(_ClusterModelFacade):
    """MultiLayerNetwork + TrainingMaster (reference
    ``SparkDl4jMultiLayer.java:77``)."""


class ClusterComputationGraph(_ClusterModelFacade):
    """ComputationGraph + TrainingMaster (reference
    ``SparkComputationGraph.java:156-182``). Data is DataSets or
    MultiDataSets — the replica step maps over the input/label list
    pytree."""


# ---------------------------------------------------------------------------
# Export-based data path
# ---------------------------------------------------------------------------


def batch_and_export_datasets(iterator, export_dir: str,
                              prefix: str = "dataset") -> List[str]:
    """Save every minibatch as an .npz file; returns paths (reference
    ``BatchAndExportDataSetsFunction`` — saves minibatch files so
    training can stream from storage instead of RAM)."""
    os.makedirs(export_dir, exist_ok=True)
    paths = []
    for i, ds in enumerate(iter(iterator)):
        path = os.path.join(export_dir, f"{prefix}_{i:06d}.npz")
        ds.save_npz(path)
        paths.append(path)
    return paths


class PathDataSetIterator(DataSetIterator):
    """Stream DataSets from exported .npz paths (reference
    ``spark/iterator/PathSparkDataSetIterator``)."""

    def __init__(self, paths: List[str]):
        if isinstance(paths, str):
            paths = sorted(glob.glob(os.path.join(paths, "*.npz")))
        self.paths = list(paths)
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self.paths)

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        ds = DataSet.load_npz(self.paths[self._pos])
        self._pos += 1
        return ds

    def reset(self) -> None:
        self._pos = 0
