"""Pipeline parallelism: GPipe-style microbatch schedule over a
``pipe`` mesh axis.

Net-new capability vs the reference (SURVEY.md §2.4: the reference has
no PP), built the TPU way: each device owns one stage's parameters,
microbatches flow stage-to-stage over ICI via ``lax.ppermute``, and
the whole schedule — fill, steady state, drain — is one ``lax.scan``
inside ``shard_map``, so XLA overlaps each hop's transfer with the
next microbatch's compute. Differentiable end-to-end (the transpose of
``ppermute`` is the reverse permute), so ``jax.grad`` of a loss on the
pipeline output yields per-stage parameter gradients with activations
rematerialized per microbatch — GPipe's memory trade.

Constraints (the classic homogeneous-pipeline shape): every stage maps
[mb, d] -> [mb, d] with the same pytree structure of per-stage params
stacked on a leading ``n_stages`` axis (transformer-block stacks fit
naturally; put embedding/head outside or fold into first/last stage
fns).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.compat import shard_map_compat as _shard_map


def _loss_cache_key(fn):
    """Cache key for a loss callable: (code, closure values) when
    hashable — same-body lambdas share a compile, different captured
    constants do not; falls back to the object itself."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return fn
    cells = getattr(fn, "__closure__", None) or ()
    try:
        key = (code, tuple(c.cell_contents for c in cells))
        hash(key)
        return key
    except (ValueError, TypeError):
        return fn


def build_pipe_mesh(n_stages: int, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n_stages:
        raise ValueError(
            f"{n_stages} stages need >= {n_stages} devices, have "
            f"{len(devices)}"
        )
    arr = np.asarray(devices[:n_stages])
    return Mesh(arr, axis_names=("pipe",))


def _gpipe_local(stage_params, xs, stage_fn: Callable, axis_name: str,
                 n_stages: int, n_micro: int):
    """Per-device GPipe schedule (runs inside shard_map).

    stage_params: this stage's params, leading axis already squeezed.
    xs: [n_micro, mb, d] microbatches (replicated; only stage 0 reads).
    Returns [n_micro, mb, d] outputs (non-zero on the last stage only).
    """
    idx = jax.lax.axis_index(axis_name)
    mb, d = xs.shape[1], xs.shape[2]
    total = n_micro + n_stages - 1  # fill + steady + drain
    pad = jnp.zeros((total - n_micro, mb, d), xs.dtype)
    xs_pad = jnp.concatenate([xs, pad], axis=0)
    # one hop forward around the ring; the wrap link (last -> 0)
    # carries garbage that stage 0 never reads
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def step(carry, x_t):
        recv, outs, t = carry
        inp = jnp.where(idx == 0, x_t, recv)
        out = stage_fn(stage_params, inp)
        recv_next = jax.lax.ppermute(out, axis_name, fwd)
        # last stage emits microbatch k = t - (n_stages - 1)
        k = t - (n_stages - 1)
        valid = (k >= 0) & (k < n_micro) & (idx == n_stages - 1)
        upd = jax.lax.dynamic_update_slice(
            outs, out[None], (jnp.clip(k, 0, n_micro - 1), 0, 0)
        )
        outs = jnp.where(valid, upd, outs)
        return (recv_next, outs, t + 1), None

    outs0 = jnp.zeros((n_micro, mb, d), xs.dtype)
    recv0 = jnp.zeros((mb, d), xs.dtype)
    (_, outs, _), _ = jax.lax.scan(
        step, (recv0, outs0, jnp.asarray(0, jnp.int32)), xs_pad
    )
    # replicate the last stage's outputs to every device
    outs = outs * (idx == n_stages - 1).astype(outs.dtype)
    return jax.lax.psum(outs, axis_name)


class GPipe:
    """Stage-partitioned trainer/applier (the PP runtime).

    ``stage_fn(params_i, x) -> y`` applied per stage; ``stage_params``
    pytree with leading ``n_stages`` axis on every leaf, sharded over
    the ``pipe`` mesh axis so each device holds exactly its stage.
    """

    def __init__(self, mesh: Mesh, stage_fn: Callable,
                 n_micro: int = 4, axis_name: str = "pipe"):
        self.mesh = mesh
        self.stage_fn = stage_fn
        self.axis_name = axis_name
        self.n_stages = mesh.shape[axis_name]
        self.n_micro = n_micro
        self._jit_apply = None
        self._jit_steps: dict = {}  # per loss_fn identity

    def shard_params(self, stage_params):
        """Place the [n_stages, ...] param pytree stage-per-device."""
        spec = NamedSharding(self.mesh, P(self.axis_name))
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(jnp.asarray(a), spec), stage_params
        )

    def _microbatch(self, x):
        b = x.shape[0]
        if b % self.n_micro:
            raise ValueError(
                f"batch {b} not divisible by n_micro={self.n_micro}"
            )
        return x.reshape(self.n_micro, b // self.n_micro, *x.shape[1:])

    def _build_apply(self):
        axis, n_stages, n_micro = (
            self.axis_name, self.n_stages, self.n_micro
        )
        stage_fn = self.stage_fn

        def local(params, xs):
            squeezed = jax.tree_util.tree_map(lambda a: a[0], params)
            return _gpipe_local(
                squeezed, xs, stage_fn, axis, n_stages, n_micro
            )

        sm = _shard_map()(
            local, mesh=self.mesh,
            in_specs=(P(self.axis_name), P()), out_specs=P(),
            check_rep=False,
        )

        def apply(params, x):
            xs = self._microbatch(x)
            outs = sm(params, xs)
            return outs.reshape(x.shape[0], -1)

        return apply

    def apply(self, stage_params, x):
        """Forward through the pipeline: x [batch, d] -> [batch, d]."""
        if self._jit_apply is None:
            self._jit_apply = jax.jit(self._build_apply())
        return self._jit_apply(stage_params, jnp.asarray(x))

    def train_step(self, stage_params, x, y, loss_fn: Callable,
                   lr: float = 0.01):
        """One SGD step of ``loss_fn(pipeline(x), y)`` — per-stage
        grads stay on their stage's device. Compiled once per distinct
        loss BODY + captured closure values, so inline lambdas
        re-created each call hit the cache, while a lambda closing
        over a CHANGED value correctly recompiles (the closure is
        baked into the program as constants)."""
        key = _loss_cache_key(loss_fn)
        jit_step = self._jit_steps.get(key)
        if jit_step is None:
            apply = self._build_apply()

            def step(params, x, y, lr):
                def objective(p):
                    return loss_fn(apply(p, x), y)

                loss, grads = jax.value_and_grad(objective)(params)
                new = jax.tree_util.tree_map(
                    lambda p, g: p - lr * g, params, grads
                )
                return new, loss

            jit_step = jax.jit(step)
            self._jit_steps[key] = jit_step
        return jit_step(
            stage_params, jnp.asarray(x), jnp.asarray(y),
            jnp.asarray(lr, jnp.float32),
        )
