"""Parallel & distributed training (replaces reference
``parallelism/ParallelWrapper`` and ``deeplearning4j-scaleout/spark``
with jax.sharding + XLA collectives, SURVEY.md §2.4)."""

from deeplearning4j_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    build_mesh,
    init_distributed,
    process_local_batch,
    replicated,
)
from deeplearning4j_tpu.parallel.trainer import (  # noqa: F401
    DistributedTrainer,
    default_partition_rules,
)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper  # noqa: F401
