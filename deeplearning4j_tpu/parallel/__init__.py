"""Parallel & distributed training (replaces reference
``parallelism/ParallelWrapper`` and ``deeplearning4j-scaleout/spark``
with jax.sharding + XLA collectives, SURVEY.md §2.4)."""

from deeplearning4j_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    build_mesh,
    init_distributed,
    init_distributed_elastic,
    process_local_batch,
    reform_distributed,
    replicated,
    shutdown_distributed,
)
from deeplearning4j_tpu.parallel.control_plane import (  # noqa: F401
    ControlPlaneException,
    CoordinatorLostException,
    HostFencedException,
    LeaseCoordinator,
    LeaseState,
    LocalTransport,
    RecoveryPlan,
    TcpTransport,
    WorkerAgent,
)
from deeplearning4j_tpu.parallel.cluster import (  # noqa: F401
    ClusterComputationGraph,
    ClusterDl4jMultiLayer,
    ParameterAveragingTrainingMaster,
    PathDataSetIterator,
    TrainingHook,
    TrainingMaster,
    TrainingWorker,
    batch_and_export_datasets,
)
from deeplearning4j_tpu.parallel.cluster_nlp import (  # noqa: F401
    ClusterGlove,
    ClusterSequenceVectors,
    ClusterWord2Vec,
    TextPipeline,
)
from deeplearning4j_tpu.parallel.expert import (  # noqa: F401
    ExpertParallelMoE,
    aux_load_balance_loss,
    build_expert_mesh,
    init_moe_params,
    moe_ffn_reference,
    switch_dispatch,
)
from deeplearning4j_tpu.parallel.pipeline import (  # noqa: F401
    GPipe,
    build_pipe_mesh,
)
from deeplearning4j_tpu.parallel.sequence import (  # noqa: F401
    attention,
    build_seq_mesh,
    ring_attention,
    ring_self_attention_sharded,
)
from deeplearning4j_tpu.parallel.dispatch import (  # noqa: F401
    AsyncDispatchWindow,
)
from deeplearning4j_tpu.parallel.elastic import (  # noqa: F401
    DeviceLostException,
    ElasticTrainer,
    HeartbeatMonitor,
    HostElasticTrainer,
    SnapshotRing,
    StragglerDetector,
)
from deeplearning4j_tpu.parallel.trainer import (  # noqa: F401
    DistributedTrainer,
    default_partition_rules,
)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper  # noqa: F401
