"""Structured tracing: spans with explicit context handoff.

Counters say *how many* requests were shed or steps skipped; only a
trace says *why this one was slow* — was it queue wait, batch
assembly, an XLA recompile, a checkpoint restore mid-rollback? The
``Tracer``/``Span`` API here is deliberately tiny (a subset of the
OpenTelemetry shape) and built for this runtime's two awkward
realities:

- **threads, not coroutines**: a serving request crosses the handler
  thread, the admission path, and the MicroBatcher drain thread.
  There is no ambient context to ride on, so context handoff is
  EXPLICIT: the admitted work item carries its ``Span`` (or
  ``SpanContext``), and the drain thread starts children from it.
  One trace id follows the request end to end.
- **determinism is a test primitive**: ids come from a seeded RNG
  (``Tracer(seed=...)``), so a pinned seed replays the exact same
  trace/span ids — chaos runs and golden files can assert on them.

Finished spans land in a bounded in-memory ring (for tests and
``finished_spans()`` inspection) and, when a sink is attached, as
JSONL — one object per span/event — via ``JsonlSink`` (bounded by
rotation: at most ~2x ``max_bytes`` on disk, oldest half dropped).

A module-global tracer (default: disabled, every operation a no-op
costing one branch) lets low-level primitives — checkpoint
save/restore, retry attempts, breaker transitions, the profiler —
emit events without threading a tracer through every constructor:
``set_global_tracer(Tracer(...))`` turns them on.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Union


class SpanContext:
    """The portable identity of a span: what you hand to another
    thread so its spans join your trace."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:
        return f"SpanContext({self.trace_id}, {self.span_id})"


class Span:
    """One named, timed operation. End it exactly once (``end()`` or
    the context-manager form, which also marks error status on an
    exception). Attribute/event mutation is single-writer by
    convention (the thread that owns the span)."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "start_time", "end_time", "attrs", "events", "status",
                 "_ended")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str],
                 start_time: float, attrs: Optional[dict] = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_time = start_time
        self.end_time: Optional[float] = None
        self.attrs: Dict[str, object] = dict(attrs) if attrs else {}
        self.events: List[dict] = []
        self.status = "ok"
        self._ended = False

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, key: str, value) -> "Span":
        self.attrs[key] = value
        return self

    def add_event(self, name: str, **attrs) -> "Span":
        self.events.append({
            "name": name, "time": self.tracer.clock(), "attrs": attrs,
        })
        return self

    def end(self, status: Optional[str] = None) -> None:
        if self._ended:  # idempotent: double-end keeps the first record
            return
        self._ended = True
        if status is not None:
            self.status = status
        self.end_time = self.tracer.clock()
        self.tracer._finish(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None and self.status == "ok":
            self.status = "error"
            self.attrs.setdefault("error_type", exc_type.__name__)
        self.end()

    def to_dict(self) -> dict:
        return {
            "kind": "span",
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start_time,
            "end": self.end_time,
            "duration_ms": (
                (self.end_time - self.start_time) * 1000.0
                if self.end_time is not None else None
            ),
            "status": self.status,
            "attrs": self.attrs,
            "events": self.events,
        }


class _NoopSpan:
    """Shared do-nothing span for disabled tracers: the hot path pays
    one flag check + one attribute lookup, nothing else."""

    __slots__ = ()
    context = SpanContext("", "")
    trace_id = ""
    span_id = ""

    def set_attr(self, key, value):
        return self

    def add_event(self, name, **attrs):
        return self

    def end(self, status=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        pass


NOOP_SPAN = _NoopSpan()


class JsonlSink:
    """Bounded JSONL span/event sink: one JSON object per line,
    flushed per write (a crash loses at most the in-flight line).
    When the live file exceeds ``max_bytes`` it rotates to
    ``<path>.1`` (replacing the previous rotation), so disk usage is
    bounded at ~2x ``max_bytes`` however long the process runs."""

    def __init__(self, path, max_bytes: int = 8 << 20):
        if max_bytes < 1:
            raise ValueError("max_bytes must be >= 1")
        self.path = os.fspath(path)
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._f = open(self.path, "a", encoding="utf-8")
        self._size = self._f.tell()
        self.written = 0
        self.rotations = 0

    def write(self, record: dict) -> None:
        line = json.dumps(record, default=str) + "\n"
        data = line.encode("utf-8")
        with self._lock:
            if self._size + len(data) > self.max_bytes and self._size:
                self._f.close()
                os.replace(self.path, self.path + ".1")
                self._f = open(self.path, "a", encoding="utf-8")
                self._size = 0
                self.rotations += 1
            self._f.write(line)
            self._f.flush()
            self._size += len(data)
            self.written += 1

    def close(self) -> None:
        with self._lock:
            self._f.close()


class Tracer:
    """Span factory + finished-span collector (see module docstring).

    ``seed`` pins the id sequence (deterministic traces under test);
    ``clock`` is injectable; ``sink`` receives every finished span as
    a dict (``JsonlSink`` or anything with ``write(dict)``);
    ``enabled=False`` makes every operation a no-op."""

    def __init__(self, seed: Optional[int] = None, sink=None,
                 clock: Callable[[], float] = time.monotonic,
                 max_finished: int = 2048, enabled: bool = True):
        self.enabled = enabled
        self.clock = clock
        self.sink = sink
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._finished: "deque[Span]" = deque(maxlen=max_finished)

    def _new_ids(self) -> "tuple[str, str]":
        with self._lock:
            return (f"{self._rng.getrandbits(128):032x}",
                    f"{self._rng.getrandbits(64):016x}")

    def _child_id(self) -> str:
        with self._lock:
            return f"{self._rng.getrandbits(64):016x}"

    def start_span(self, name: str,
                   parent: Union[Span, SpanContext, None] = None,
                   attrs: Optional[dict] = None) -> Union[Span, _NoopSpan]:
        if not self.enabled:
            return NOOP_SPAN
        if isinstance(parent, _NoopSpan):
            parent = None
        if parent is not None and parent.trace_id:
            trace_id = parent.trace_id
            parent_id: Optional[str] = parent.span_id
            span_id = self._child_id()
        else:
            trace_id, span_id = self._new_ids()
            parent_id = None
        return Span(self, name, trace_id, span_id, parent_id,
                    self.clock(), attrs)

    def event(self, name: str, attrs: Optional[dict] = None,
              parent: Union[Span, SpanContext, None] = None) -> None:
        """A zero-duration record (breaker tripped, compile observed,
        retry attempt N failed) — a span whose start == end."""
        if not self.enabled:
            return
        span = self.start_span(name, parent=parent, attrs=attrs)
        span.end()

    def _finish(self, span: Span) -> None:
        with self._lock:
            self._finished.append(span)
        if self.sink is not None:
            try:
                self.sink.write(span.to_dict())
            except Exception:
                pass  # telemetry must never take down the work

    def finished_spans(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


# -- global tracer ------------------------------------------------------

_global_tracer = Tracer(enabled=False)
_global_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer low-level primitives (checkpoint,
    retry, breaker, profiler) emit through. Disabled by default —
    enable with ``set_global_tracer``."""
    return _global_tracer


def set_global_tracer(tracer: Tracer) -> Tracer:
    """Install ``tracer`` globally; returns the previous one so tests
    can restore it."""
    global _global_tracer
    with _global_lock:
        prev = _global_tracer
        _global_tracer = tracer
        return prev
