"""Shared metrics substrate: one registry, four instrument kinds.

Before this module the repo had re-grown the reference's telemetry
gap three times over: ``serving/metrics.py`` kept a private counter
dict + latency reservoir, ``ui/stats_listener.py`` hand-rolled numpy
histograms, and ``optimize/profiler.py`` only ever *returned* its
trace location. The TensorFlow system paper credits much of its
operability to built-in monitoring of step time, queue depth, and
compilation events (PAPERS.md) — signals that only compose into one
dashboard when every subsystem registers them in one place, with one
export format.

Design:

- ``MetricsRegistry`` hands out **families** by name —
  ``counter`` / ``gauge`` / ``histogram`` (fixed upper bounds,
  cumulative at export) / ``summary`` (quantile reservoir). A family
  with ``labels=(...)`` fans out into labeled children via
  ``.labels(...)``; an unlabeled family IS its single instrument.
  Registration is idempotent by name (re-registering returns the
  existing family; a kind mismatch raises), so independent listeners
  can share one signal.
- Everything is **thread-safe**: a per-instrument lock guards each
  update, a registry lock guards family creation. Serving worker
  pools and training listener threads hammer the same counters.
- The **clock is injectable** and the registry has a **no-op mode**
  (``enabled=False`` or ``enable(False)``): every instrument checks
  one flag and returns, so a disabled registry prices the
  instrumented hot path at one attribute read + one branch —
  ``bench.py``'s ``observability_overhead`` section holds that claim
  to <= 5%.
- Export lives in ``export.py`` (Prometheus text exposition + JSON
  snapshot); trace correlation in ``trace.py``.

The canonical ``Reservoir`` (ring of recent observations,
nearest-rank quantiles) and fixed-boundary ``Histogram`` live here;
``serving/metrics.py`` re-exports them so existing imports keep
working. The array-summary helpers the UI stats listener uses
(``mean_magnitudes``, ``array_histograms``) are also here — one
implementation for every consumer of "summarize this param tree".
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"
SUMMARY = "summary"


class Reservoir:
    """Ring buffer of the last ``size`` observations with
    nearest-rank quantiles. Bounded memory however long the process
    runs; recency bias is the point — dashboards want "how slow is it
    NOW", not a since-boot average."""

    def __init__(self, size: int = 1024):
        if size < 1:
            raise ValueError("size must be >= 1")
        self.size = size
        self._ring: List[float] = []
        self._next = 0
        self.count = 0   # total ever recorded
        self.total = 0.0  # running sum (Prometheus summary _sum)

    def record(self, value: float) -> None:
        if len(self._ring) < self.size:
            self._ring.append(value)
        else:
            self._ring[self._next] = value
        self._next = (self._next + 1) % self.size
        self.count += 1
        self.total += value

    def quantile(self, q: float) -> Optional[float]:
        if not self._ring:
            return None
        s = sorted(self._ring)
        idx = min(len(s) - 1, max(0, int(q * len(s))))
        return s[idx]

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            "max": max(self._ring) if self._ring else None,
        }


class Histogram:
    """Fixed-boundary counting histogram: ``record(v)`` counts v into
    the first boundary >= v (an overflow bin catches the rest).
    Bounded memory, O(log b) record. ``cumulative()`` yields the
    Prometheus view: (upper_bound, cumulative_count) pairs ending at
    +Inf == total count."""

    def __init__(self, boundaries: Sequence[float]):
        if not boundaries:
            raise ValueError("histogram needs at least one boundary")
        self.boundaries = sorted(float(b) for b in boundaries)
        self._counts = [0] * (len(self.boundaries) + 1)
        self.count = 0
        self.total = 0.0

    def record(self, value: float) -> None:
        self._counts[bisect.bisect_left(self.boundaries, value)] += 1
        self.count += 1
        self.total += value

    def cumulative(self) -> List[Tuple[float, int]]:
        out = []
        running = 0
        for b, c in zip(self.boundaries, self._counts):
            running += c
            out.append((b, running))
        out.append((float("inf"), running + self._counts[-1]))
        return out

    def snapshot(self) -> dict:
        buckets = {}
        for b, c in zip(self.boundaries, self._counts):
            buckets[f"le_{b:g}"] = c
        buckets["overflow"] = self._counts[-1]
        return {
            "count": self.count,
            "mean": (self.total / self.count) if self.count else None,
            "buckets": buckets,
        }


# -- array-summary helpers (shared with the UI stats listener) ----------


def mean_magnitudes(tree: dict) -> dict:
    """``{layer: {param: array}}`` -> ``{"layer_param": mean |x|}``."""
    import numpy as np

    out = {}
    for lname, params in tree.items():
        for pname, arr in params.items():
            a = np.asarray(arr)
            out[f"{lname}_{pname}"] = float(np.mean(np.abs(a)))
    return out


def array_histograms(tree: dict, bins: int = 20) -> dict:
    """Per-param value histograms of a param tree (the UI's histogram
    tab payload: min/max/counts per ``layer_param``)."""
    import numpy as np

    out = {}
    for lname, params in tree.items():
        for pname, arr in params.items():
            a = np.asarray(arr).ravel()
            counts, edges = np.histogram(a, bins=bins)
            out[f"{lname}_{pname}"] = {
                "min": float(edges[0]), "max": float(edges[-1]),
                "counts": counts.tolist(),
            }
    return out


# -- instruments --------------------------------------------------------


class _Instrument:
    """One time series: a (family, label values) pair. All updates
    take the instrument lock; the registry's enabled flag is checked
    first so no-op mode costs one branch."""

    __slots__ = ("family", "label_values", "_lock")

    def __init__(self, family: "Family", label_values: Tuple[str, ...]):
        self.family = family
        self.label_values = label_values
        self._lock = threading.Lock()


class Counter(_Instrument):
    __slots__ = ("_value",)

    def __init__(self, family, label_values):
        super().__init__(family, label_values)
        self._value = 0

    def inc(self, n: float = 1) -> None:
        if not self.family.registry.enabled:
            return
        if n < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge(_Instrument):
    __slots__ = ("_value",)

    def __init__(self, family, label_values):
        super().__init__(family, label_values)
        self._value = 0.0

    def set(self, v: float) -> None:
        if not self.family.registry.enabled:
            return
        with self._lock:
            self._value = v

    def add(self, n: float = 1) -> None:
        if not self.family.registry.enabled:
            return
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value


class HistogramInstrument(_Instrument):
    __slots__ = ("hist",)

    def __init__(self, family, label_values):
        super().__init__(family, label_values)
        self.hist = Histogram(family.buckets)

    def observe(self, v: float) -> None:
        if not self.family.registry.enabled:
            return
        with self._lock:
            self.hist.record(v)

    def snapshot(self) -> dict:
        with self._lock:
            return self.hist.snapshot()

    def cumulative(self) -> List[Tuple[float, int]]:
        with self._lock:
            return self.hist.cumulative()

    @property
    def count(self) -> int:
        with self._lock:
            return self.hist.count

    @property
    def total(self) -> float:
        with self._lock:
            return self.hist.total


class SummaryInstrument(_Instrument):
    __slots__ = ("reservoir",)

    def __init__(self, family, label_values):
        super().__init__(family, label_values)
        self.reservoir = Reservoir(family.reservoir_size)

    def observe(self, v: float) -> None:
        if not self.family.registry.enabled:
            return
        with self._lock:
            self.reservoir.record(v)

    def snapshot(self) -> dict:
        with self._lock:
            return self.reservoir.snapshot()

    def quantile_values(self) -> List[Tuple[float, Optional[float]]]:
        with self._lock:
            return [
                (q, self.reservoir.quantile(q))
                for q in self.family.quantiles
            ]

    @property
    def count(self) -> int:
        with self._lock:
            return self.reservoir.count

    @property
    def total(self) -> float:
        with self._lock:
            return self.reservoir.total


_KIND_CLASSES = {
    COUNTER: Counter,
    GAUGE: Gauge,
    HISTOGRAM: HistogramInstrument,
    SUMMARY: SummaryInstrument,
}


class Family:
    """All time series sharing one metric name. With ``label_names``
    empty the family proxies straight to its single child, so
    ``registry.counter("x").inc()`` works; with labels,
    ``family.labels("a")`` / ``family.labels(model="a")`` returns the
    child for those values (creating it on first use)."""

    def __init__(self, registry: "MetricsRegistry", name: str,
                 kind: str, help: str, label_names: Tuple[str, ...],
                 buckets: Optional[Sequence[float]] = None,
                 reservoir_size: int = 1024,
                 quantiles: Sequence[float] = (0.5, 0.9, 0.99)):
        self.registry = registry
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = tuple(label_names)
        self.buckets = list(buckets) if buckets is not None else None
        self.reservoir_size = reservoir_size
        self.quantiles = tuple(quantiles)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Instrument] = {}
        # unlabeled families cache their single child so the proxy
        # methods below are one attribute hop (hot-path cost)
        self._child0: Optional[_Instrument] = None
        if not self.label_names:
            self._child0 = _KIND_CLASSES[kind](self, ())
            self._children[()] = self._child0

    def labels(self, *values, **kv) -> _Instrument:
        if kv:
            if values:
                raise ValueError("pass labels positionally OR by name")
            try:
                values = tuple(str(kv[n]) for n in self.label_names)
            except KeyError as e:
                raise ValueError(
                    f"metric {self.name!r} needs labels "
                    f"{self.label_names}, got {tuple(kv)}"
                ) from e
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"metric {self.name!r} takes {len(self.label_names)} "
                f"label(s) {self.label_names}, got {len(values)}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = _KIND_CLASSES[self.kind](self, values)
                self._children[values] = child
            return child

    def children(self) -> List[_Instrument]:
        with self._lock:
            return list(self._children.values())

    # -- unlabeled proxy ------------------------------------------------

    def _default(self) -> _Instrument:
        if self._child0 is None:
            raise ValueError(
                f"metric {self.name!r} has labels {self.label_names}; "
                "call .labels(...) first"
            )
        return self._child0

    def inc(self, n: float = 1) -> None:
        self._default().inc(n)

    def set(self, v: float) -> None:
        self._default().set(v)

    def add(self, n: float = 1) -> None:
        self._default().add(n)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    @property
    def value(self):
        return self._default().value

    def snapshot(self):
        return self._default().snapshot()


class MetricsRegistry:
    """Thread-safe instrument registry (see module docstring).

    ``enabled=False`` (or ``enable(False)`` later) flips every
    instrument into no-op mode: registration still works — the signal
    catalog stays complete — but updates return after one branch.
    The ``clock`` is carried for consumers that time things against
    the registry (injectable so tests advance time manually)."""

    def __init__(self, enabled: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        self.enabled = enabled
        self.clock = clock
        self._lock = threading.Lock()
        self._families: Dict[str, Family] = {}

    def enable(self, on: bool = True) -> None:
        self.enabled = on

    def _register(self, name: str, kind: str, help: str,
                  labels: Sequence[str], **opts) -> Family:
        if not _NAME_RE.fullmatch(name):
            raise ValueError(
                f"metric name {name!r} is not Prometheus-legal "
                "([a-zA-Z_:][a-zA-Z0-9_:]*)"
            )
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {kind}"
                    )
                return fam
            fam = Family(self, name, kind, help, tuple(labels), **opts)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Family:
        return self._register(name, COUNTER, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Family:
        return self._register(name, GAUGE, help, labels)

    def histogram(self, name: str, buckets: Sequence[float],
                  help: str = "", labels: Sequence[str] = ()) -> Family:
        return self._register(name, HISTOGRAM, help, labels,
                              buckets=buckets)

    def summary(self, name: str, reservoir_size: int = 1024,
                quantiles: Sequence[float] = (0.5, 0.9, 0.99),
                help: str = "", labels: Sequence[str] = ()) -> Family:
        return self._register(name, SUMMARY, help, labels,
                              reservoir_size=reservoir_size,
                              quantiles=quantiles)

    def collect(self) -> List[Family]:
        with self._lock:
            return list(self._families.values())

    def names(self) -> List[str]:
        with self._lock:
            return list(self._families)

    def get(self, name: str) -> Optional[Family]:
        with self._lock:
            return self._families.get(name)


# A process-wide default registry: training-side listeners publish
# here unless handed their own, and the UI server's /metrics scrapes
# it. Serving keeps a per-ModelServer registry (isolated counters per
# server instance).
_default_registry = MetricsRegistry()

# A shared always-disabled registry for "instrumented but off".
NULL_REGISTRY = MetricsRegistry(enabled=False)


def default_registry() -> MetricsRegistry:
    return _default_registry
