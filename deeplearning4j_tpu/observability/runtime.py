"""JAX runtime telemetry: device memory, compile events, train-step
signals.

The TensorFlow system paper (PAPERS.md) credits built-in monitoring
of step time, queue depth, and compilation events for much of its
operability — on a TPU stack those are exactly the signals that
explain why a step or request was slow (XLA recompile? queue wait?
device sync?). The serving side already counts compiles
(``serving/compile_cache.py`` feeds ``xla_compiles_total`` and, with
a tracer attached, per-shape ``xla.compile`` events); this module
adds the training side:

- ``device_memory_stats()`` / ``publish_device_memory()``: per-device
  HBM usage via ``jax.local_devices()[i].memory_stats()`` when the
  backend exposes it (TPU does; CPU usually returns nothing — the
  gauges simply stay absent).
- ``TelemetryListener``: an ``IterationListener`` publishing step
  time, loss, gradient global-norm, and examples/sec into a
  ``MetricsRegistry`` from BOTH engines' fit loops —
  ``MultiLayerNetwork`` and ``DistributedTrainer`` invoke the same
  listener SPI. Grad global-norm is computed *in-jit* (the engines'
  step telemetry mode adds one fused scalar output; see
  ``enable_step_telemetry``), not by a second host-side pass.

TPU note (same design as ``StatsListener``): reading loss or grad
norm forces a device sync, so those reads are gated by ``frequency``;
the step-time/throughput instruments are pure host clock reads and
run every iteration.
"""

from __future__ import annotations

import time
from typing import Optional

from deeplearning4j_tpu.observability.metrics import (
    MetricsRegistry,
    default_registry,
)
from deeplearning4j_tpu.optimize.listeners import IterationListener


def device_memory_stats() -> dict:
    """``{device_index: memory_stats dict}`` for every local device
    that reports one (``memory_stats()`` is backend-optional)."""
    import jax

    out = {}
    for i, d in enumerate(jax.local_devices()):
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[str(i)] = dict(stats)
    return out


def publish_device_memory(
    registry: Optional[MetricsRegistry] = None,
) -> dict:
    """Publish per-device HBM gauges into ``registry`` (default: the
    process-wide registry). Returns the raw stats so callers can log
    them too. A backend with no ``memory_stats()`` publishes
    nothing."""
    reg = registry if registry is not None else default_registry()
    stats = device_memory_stats()
    if not stats:
        return stats
    in_use = reg.gauge(
        "jax_device_memory_bytes_in_use",
        help="per-device HBM bytes currently allocated",
        labels=("device",),
    )
    peak = reg.gauge(
        "jax_device_memory_peak_bytes",
        help="per-device peak HBM bytes since process start",
        labels=("device",),
    )
    for dev, ms in stats.items():
        if "bytes_in_use" in ms:
            in_use.labels(device=dev).set(float(ms["bytes_in_use"]))
        if "peak_bytes_in_use" in ms:
            peak.labels(device=dev).set(float(ms["peak_bytes_in_use"]))
    return stats


class TelemetryListener(IterationListener):
    """Publish train-step telemetry into a ``MetricsRegistry``.

    Signals (catalogued in ARCHITECTURE.md):

    - ``training_steps_total`` / ``training_examples_total`` counters
      (every iteration; host-only, no device sync);
    - ``training_step_ms`` summary + ``training_examples_per_sec``
      gauge (host wall clock between callbacks);
    - ``training_loss`` gauge (gated by ``frequency``);
    - ``training_grad_global_norm`` gauge: the in-jit fused scalar
      the engines' telemetry step emits. The listener flips the
      model's ``enable_step_telemetry()`` on first callback; engines
      without the hook (or before the first telemetry step) simply
      don't publish the gauge;
    - per-device HBM gauges via ``publish_device_memory`` when
      ``publish_memory=True`` and the backend reports memory stats.

    **Batched host reads** (``defer_reads=True``, the default): the
    sampled device scalars (loss, grad norm) are NOT converted in the
    callback that sampled them — that ``float()`` would block until
    the step completes, serializing dispatch against execution
    (exactly the per-step sync the async fit loop removes). Instead
    the listener holds the device references and publishes them on
    the NEXT sampled callback, by which time the step has long
    retired and the read is a copy, not a stall; ``flush()`` (also
    run from ``on_epoch_end``) publishes the final pending sample.
    The published value therefore trails by one sampling interval.
    ``defer_reads=False`` restores the synchronous read.

    Forces the per-step fit path under scan-chunked epochs (like
    ``ProfilerListener``): there all callbacks would fire after one
    chunk dispatch, so per-step timing would be fiction. Under
    MEGASTEP epochs the listener rides ``chunk_done`` instead — one
    honest per-chunk sample from the driver's single readback.
    """

    supports_batched_iterations = False

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 frequency: int = 1, grad_norm: bool = True,
                 publish_memory: bool = True,
                 defer_reads: bool = True):
        self.registry = (
            registry if registry is not None else default_registry()
        )
        self.frequency = max(int(frequency), 1)
        self.grad_norm = grad_norm
        self.publish_memory = publish_memory
        reg = self.registry
        # resolved unlabeled instruments (not family proxies): one
        # attribute hop per update on the per-step hot path
        self._steps = reg.counter(
            "training_steps_total", help="optimizer steps completed"
        )._default()
        self._examples = reg.counter(
            "training_examples_total",
            help="training examples consumed",
        )._default()
        self._loss = reg.gauge(
            "training_loss", help="latest minibatch loss (sampled)"
        )._default()
        self._grad_norm = reg.gauge(
            "training_grad_global_norm",
            help="gradient global L2 norm of the sampled step",
        )._default()
        self._eps = reg.gauge(
            "training_examples_per_sec",
            help="host-clocked examples/sec over the last step",
        )._default()
        self._step_ms = reg.summary(
            "training_step_ms",
            help="host wall-clock per optimizer step (ms)",
        )._default()
        # whole-net transform signals (nn/core.py knobs on the model)
        self._remat_enabled = reg.gauge(
            "remat_enabled",
            help="1 when activation rematerialization is active",
        )._default()
        self._scan_runs = reg.gauge(
            "scan_layer_runs",
            help="scanned homogeneous layer runs in the active model",
        )._default()
        self._loss_scale = reg.gauge(
            "loss_scale_value",
            help="current dynamic loss scale (float16 training)",
        )._default()
        self._ls_overflows = reg.counter(
            "loss_scale_overflows_total",
            help="loss-scale overflow steps (update skipped in-jit, "
                 "scale halved)",
        )._default()
        self._ls_overflows_seen = 0
        self._last_time: Optional[float] = None
        self._enabled_on = None
        self.defer_reads = defer_reads
        self._pending = None  # (loss_ref, grad_norm_ref) device refs

    def _publish_sample(self, loss_ref, gn_ref) -> None:
        if loss_ref is not None:
            try:
                self._loss.set(float(loss_ref))
            except Exception:
                pass
        if gn_ref is not None:
            try:
                self._grad_norm.set(float(gn_ref))
            except Exception:
                pass

    def flush(self) -> None:
        """Publish the pending deferred sample (epoch end / end of
        fit)."""
        pending, self._pending = self._pending, None
        if pending is not None:
            self._publish_sample(*pending)

    def on_epoch_end(self, model) -> None:
        self.flush()

    def iteration_done(self, model, iteration: int) -> None:
        now = time.perf_counter()
        if (self.grad_norm and self.registry.enabled
                and self._enabled_on is not model):
            # don't compile the in-jit grad-norm output when the
            # registry is a no-op — nobody would read the signal
            enable = getattr(model, "enable_step_telemetry", None)
            if enable is not None:
                enable(True)
            self._enabled_on = model
        if not self.registry.enabled:  # no-op mode: one branch out
            self._last_time = now
            return
        rows = getattr(model, "_last_batch_rows", None)
        self._steps.inc()
        if rows:
            self._examples.inc(int(rows))
        if self._last_time is not None:
            dt = now - self._last_time
            self._step_ms.observe(dt * 1000.0)
            if rows and dt > 0:
                self._eps.set(int(rows) / dt)
        self._last_time = now
        if iteration % self.frequency != 0:
            return
        # below the line: sampled device scalars, gated by frequency
        loss_ref = getattr(model, "_last_score", None)
        gn_ref = getattr(model, "_last_grad_norm", None)
        if self.defer_reads:
            # publish LAST sample's refs (long since completed — the
            # read is a copy, not a pipeline stall), park this one
            pending, self._pending = self._pending, (loss_ref, gn_ref)
            if pending is not None:
                self._publish_sample(*pending)
        else:
            self._publish_sample(loss_ref, gn_ref)
        self._publish_transforms(model)
        if self.publish_memory:
            publish_device_memory(self.registry)

    def chunk_done(self, model, it0: int, k: int, metrics) -> None:
        """Megastep cadence: ONE callback per fused K-step chunk, fed
        the chunk's already-host metric dict — publishing here costs
        ZERO extra device syncs (the driver's single per-chunk
        readback paid them all). Counters advance by the whole chunk,
        the loss/grad-norm gauges publish the chunk's last step, and
        the transform gauges + memory stats stay frequency-gated in
        STEPS, so the one genuinely-blocking read (the loss-scale
        device dict) still happens at most once per sampling
        interval."""
        now = time.perf_counter()
        if (self.grad_norm and self.registry.enabled
                and self._enabled_on is not model):
            enable = getattr(model, "enable_step_telemetry", None)
            if enable is not None:
                enable(True)
            self._enabled_on = model
        if not self.registry.enabled:
            self._last_time = now
            return
        rows = int(metrics.get("examples", 0) or 0)
        self._steps.inc(int(k))
        if rows:
            self._examples.inc(rows)
        if self._last_time is not None:
            dt = now - self._last_time
            if k > 0:
                self._step_ms.observe(dt * 1000.0 / k)
            if rows and dt > 0:
                self._eps.set(rows / dt)
        self._last_time = now
        if (it0 + k) // self.frequency == it0 // self.frequency:
            return  # no sampling boundary inside this chunk
        scores = metrics.get("scores")
        if scores is not None and len(scores):
            self._loss.set(float(scores[-1]))
        gns = metrics.get("grad_norms")
        if gns is not None and len(gns):
            self._grad_norm.set(float(gns[-1]))
        self._publish_transforms(model)
        if self.publish_memory:
            publish_device_memory(self.registry)

    def _publish_transforms(self, model) -> None:
        """Whole-net transform gauges, sampled with the loss (the
        loss-scale state is a device dict — reading it here rides the
        same gated sync)."""
        self._remat_enabled.set(
            1.0 if getattr(model, "remat", "none") != "none" else 0.0
        )
        count = getattr(model, "scan_layer_run_count", None)
        if count is not None:
            try:
                self._scan_runs.set(float(count()))
            except Exception:
                pass
        ls = getattr(model, "_loss_scale_state", None)
        if ls is not None:
            try:
                self._loss_scale.set(float(ls["scale"]))
                seen = int(ls["overflows"])
                if seen > self._ls_overflows_seen:
                    self._ls_overflows.inc(
                        seen - self._ls_overflows_seen
                    )
                    from deeplearning4j_tpu.observability import (
                        flightrec,
                    )
                    flightrec.record_event(
                        "loss_scale_overflow",
                        overflows=seen,
                        new=seen - self._ls_overflows_seen,
                        scale=float(ls["scale"]),
                    )
                self._ls_overflows_seen = seen
            except Exception:
                pass
