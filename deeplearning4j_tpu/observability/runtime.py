"""JAX runtime telemetry: device memory, compile events, train-step
signals.

The TensorFlow system paper (PAPERS.md) credits built-in monitoring
of step time, queue depth, and compilation events for much of its
operability — on a TPU stack those are exactly the signals that
explain why a step or request was slow (XLA recompile? queue wait?
device sync?). The serving side already counts compiles
(``serving/compile_cache.py`` feeds ``xla_compiles_total`` and, with
a tracer attached, per-shape ``xla.compile`` events); this module
adds the training side:

- ``device_memory_stats()`` / ``publish_device_memory()``: per-device
  HBM usage via ``jax.local_devices()[i].memory_stats()`` when the
  backend exposes it (TPU does; CPU usually returns nothing — the
  gauges simply stay absent).
- ``TelemetryListener``: an ``IterationListener`` publishing step
  time, loss, gradient global-norm, and examples/sec into a
  ``MetricsRegistry`` from BOTH engines' fit loops —
  ``MultiLayerNetwork`` and ``DistributedTrainer`` invoke the same
  listener SPI. Grad global-norm is computed *in-jit* (the engines'
  step telemetry mode adds one fused scalar output; see
  ``enable_step_telemetry``), not by a second host-side pass.

TPU note (same design as ``StatsListener``): reading loss or grad
norm forces a device sync, so those reads are gated by ``frequency``;
the step-time/throughput instruments are pure host clock reads and
run every iteration.
"""

from __future__ import annotations

import time
from typing import Optional

from deeplearning4j_tpu.observability.metrics import (
    MetricsRegistry,
    default_registry,
)
from deeplearning4j_tpu.optimize.listeners import IterationListener


def device_memory_stats() -> dict:
    """``{device_index: memory_stats dict}`` for every local device
    that reports one (``memory_stats()`` is backend-optional)."""
    import jax

    out = {}
    for i, d in enumerate(jax.local_devices()):
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[str(i)] = dict(stats)
    return out


def publish_device_memory(
    registry: Optional[MetricsRegistry] = None,
) -> dict:
    """Publish per-device HBM gauges into ``registry`` (default: the
    process-wide registry). Returns the raw stats so callers can log
    them too. A backend with no ``memory_stats()`` publishes
    nothing."""
    reg = registry if registry is not None else default_registry()
    stats = device_memory_stats()
    if not stats:
        return stats
    in_use = reg.gauge(
        "jax_device_memory_bytes_in_use",
        help="per-device HBM bytes currently allocated",
        labels=("device",),
    )
    peak = reg.gauge(
        "jax_device_memory_peak_bytes",
        help="per-device peak HBM bytes since process start",
        labels=("device",),
    )
    for dev, ms in stats.items():
        if "bytes_in_use" in ms:
            in_use.labels(device=dev).set(float(ms["bytes_in_use"]))
        if "peak_bytes_in_use" in ms:
            peak.labels(device=dev).set(float(ms["peak_bytes_in_use"]))
    return stats


class TelemetryListener(IterationListener):
    """Publish train-step telemetry into a ``MetricsRegistry``.

    Signals (catalogued in ARCHITECTURE.md):

    - ``training_steps_total`` / ``training_examples_total`` counters
      (every iteration; host-only, no device sync);
    - ``training_step_ms`` summary + ``training_examples_per_sec``
      gauge (host wall clock between callbacks);
    - ``training_loss`` gauge (device sync — gated by ``frequency``);
    - ``training_grad_global_norm`` gauge: the in-jit fused scalar
      the engines' telemetry step emits. The listener flips the
      model's ``enable_step_telemetry()`` on first callback; engines
      without the hook (or before the first telemetry step) simply
      don't publish the gauge;
    - per-device HBM gauges via ``publish_device_memory`` when
      ``publish_memory=True`` and the backend reports memory stats.

    Forces the per-step fit path (like ``ProfilerListener``): under
    the fused ``lax.scan`` path all callbacks fire after one chunk
    dispatch, so per-step timing would be fiction.
    """

    supports_batched_iterations = False

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 frequency: int = 1, grad_norm: bool = True,
                 publish_memory: bool = True):
        self.registry = (
            registry if registry is not None else default_registry()
        )
        self.frequency = max(int(frequency), 1)
        self.grad_norm = grad_norm
        self.publish_memory = publish_memory
        reg = self.registry
        # resolved unlabeled instruments (not family proxies): one
        # attribute hop per update on the per-step hot path
        self._steps = reg.counter(
            "training_steps_total", help="optimizer steps completed"
        )._default()
        self._examples = reg.counter(
            "training_examples_total",
            help="training examples consumed",
        )._default()
        self._loss = reg.gauge(
            "training_loss", help="latest minibatch loss (sampled)"
        )._default()
        self._grad_norm = reg.gauge(
            "training_grad_global_norm",
            help="gradient global L2 norm of the sampled step",
        )._default()
        self._eps = reg.gauge(
            "training_examples_per_sec",
            help="host-clocked examples/sec over the last step",
        )._default()
        self._step_ms = reg.summary(
            "training_step_ms",
            help="host wall-clock per optimizer step (ms)",
        )._default()
        self._last_time: Optional[float] = None
        self._enabled_on = None

    def iteration_done(self, model, iteration: int) -> None:
        now = time.perf_counter()
        if (self.grad_norm and self.registry.enabled
                and self._enabled_on is not model):
            # don't compile the in-jit grad-norm output when the
            # registry is a no-op — nobody would read the signal
            enable = getattr(model, "enable_step_telemetry", None)
            if enable is not None:
                enable(True)
            self._enabled_on = model
        if not self.registry.enabled:  # no-op mode: one branch out
            self._last_time = now
            return
        rows = getattr(model, "_last_batch_rows", None)
        self._steps.inc()
        if rows:
            self._examples.inc(int(rows))
        if self._last_time is not None:
            dt = now - self._last_time
            self._step_ms.observe(dt * 1000.0)
            if rows and dt > 0:
                self._eps.set(int(rows) / dt)
        self._last_time = now
        if iteration % self.frequency != 0:
            return
        # below the line: device syncs, gated by frequency
        try:
            self._loss.set(float(model.score_value))
        except Exception:
            pass
        gn = getattr(model, "_last_grad_norm", None)
        if gn is not None:
            try:
                self._grad_norm.set(float(gn))
            except Exception:
                pass
        if self.publish_memory:
            publish_device_memory(self.registry)
