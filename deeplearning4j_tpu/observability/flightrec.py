"""Flight recorder: a bounded lock-free ring of structured per-step
records that turns "the run died at step 48k" into an inspectable
last-N-steps artifact.

Black-box philosophy (the aviation kind): recording must be cheap
enough to leave on for every run (one dict build + one slot write per
step; no locks, no I/O), and the payoff is entirely at crash time —
the ring dumps atomically (temp file + ``os.replace``, JSONL) when a
divergence guard trips, when a preemption notice lands (the dump
rides the emergency checkpoint manifest as a CRC-verified artifact —
see ``resilience/preemption.py``), when a fit loop dies on an
unhandled exception, or on demand (``GET /debugz`` serves the live
tail without dumping).

Ring entries are either **step records** (``type="step"``: step,
loss, grad-norm, timing decomposition, MFU, trace id — appended by
``observability/profiler.StepProfiler``) or **event records**
(``type="event"``: compile, guard trip, quarantine, loss-scale
overflow, preemption notice — appended by the subsystems as they
happen), interleaved in arrival order so a dump reads as a timeline.

Lock-free: slot reservation is one ``itertools.count`` draw (atomic
under CPython) and one list-slot store. Readers (``tail``/``dump``)
take a consistent-enough snapshot without stalling writers; a record
overwritten mid-snapshot is simply the ring doing its job.

Knobs: ``DL4J_TPU_FLIGHTREC_RING`` (capacity, default 512),
``DL4J_TPU_FLIGHTREC_DIR`` (dump directory, default CWD).
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import tempfile
import time
from typing import List, Optional

logger = logging.getLogger(__name__)

_ENV_RING = "DL4J_TPU_FLIGHTREC_RING"
_ENV_DIR = "DL4J_TPU_FLIGHTREC_DIR"

_DEFAULT_CAPACITY = 512
# /debugz and other live views read at most this many trailing
# records — the endpoint stays bounded no matter the ring size
DEBUG_TAIL_LIMIT = 100


def _jsonable(v):
    """Records must survive json.dumps no matter what a caller stuffs
    in (device arrays, numpy scalars): coerce scalars, stringify the
    rest. NaN/Inf become None (legal JSON, and a diverged loss is
    exactly when the dump matters)."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        return v if v == v and v not in (float("inf"),
                                         float("-inf")) else None
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    try:  # numpy / jax scalars
        return _jsonable(float(v))
    except Exception:
        return str(v)


class FlightRecorder:
    """Bounded ring of per-step records with atomic JSONL dumps."""

    def __init__(self, capacity: Optional[int] = None,
                 dump_dir: Optional[str] = None,
                 registry=None, enabled: bool = True,
                 clock=time.time):
        if capacity is None:
            capacity = int(os.environ.get(_ENV_RING,
                                          _DEFAULT_CAPACITY))
        self.capacity = max(1, int(capacity))
        self.dump_dir = dump_dir or os.environ.get(_ENV_DIR) or "."
        self.enabled = enabled
        self._clock = clock
        self._ring: List[Optional[dict]] = [None] * self.capacity
        self._seq = itertools.count()  # atomic slot reservation
        self._records_total = None
        self._dumps_total = None
        self._last_dump_step = None
        if registry is not None:
            self._records_total = registry.counter(
                "flightrec_records_total",
                help="flight recorder: records appended to the ring",
            )._default()
            self._dumps_total = registry.counter(
                "flightrec_dumps_total",
                help="flight recorder: ring dumps written, by reason",
                labels=("reason",),
            )
            self._last_dump_step = registry.gauge(
                "flightrec_last_dump_step",
                help="flight recorder: step of the newest step record "
                     "in the last dump (-1 before any dump)",
            )._default()
            self._last_dump_step.set(-1)

    # -- writers (hot path) --------------------------------------------

    def record(self, **fields) -> None:
        """Append one step record. Lock-free; cheap enough for every
        training step."""
        if not self.enabled:
            return
        seq = next(self._seq)
        rec = {"type": "step", "seq": seq, "t": self._clock()}
        rec.update(fields)
        self._ring[seq % self.capacity] = rec
        if self._records_total is not None:
            self._records_total.inc()

    def event(self, kind: str, **attrs) -> None:
        """Append one event record (compile / guard trip / quarantine
        / loss-scale overflow / preemption notice / ...)."""
        if not self.enabled:
            return
        seq = next(self._seq)
        rec = {"type": "event", "event": kind, "seq": seq,
               "t": self._clock()}
        rec.update(attrs)
        self._ring[seq % self.capacity] = rec
        if self._records_total is not None:
            self._records_total.inc()

    # -- readers --------------------------------------------------------

    def tail(self, n: Optional[int] = None) -> List[dict]:
        """Last ``n`` records (default: everything retained), oldest
        first. Snapshot read: concurrent writers may overwrite slots
        being read — entries are filtered to well-formed dicts and
        re-sorted by seq, so the result is always a consistent
        subsequence of what was recorded."""
        snap = [r for r in list(self._ring) if isinstance(r, dict)]
        snap.sort(key=lambda r: r.get("seq", 0))
        if n is not None:
            snap = snap[-int(n):]
        return snap

    def last_step(self) -> Optional[int]:
        """Step of the newest step record, or None when the ring holds
        none — the resume-step cross-check for preemption dumps."""
        for rec in reversed(self.tail()):
            if rec.get("type") == "step" and "step" in rec:
                return int(rec["step"])
        return None

    # -- dumps ----------------------------------------------------------

    def dump_bytes(self, reason: str = "on_demand") -> bytes:
        """The ring as JSONL bytes: a header line (reason, record
        count, last step, wall time) then every retained record,
        oldest first. This is what rides the emergency checkpoint
        manifest as a CRC-verified artifact."""
        records = self.tail()
        header = {
            "type": "header",
            "reason": reason,
            "records": len(records),
            "capacity": self.capacity,
            "last_step": self.last_step(),
            "t": self._clock(),
            "pid": os.getpid(),
        }
        lines = [json.dumps(_jsonable(header))]
        lines.extend(json.dumps(_jsonable(r)) for r in records)
        self._note_dump(reason)
        return ("\n".join(lines) + "\n").encode()

    def dump(self, path: Optional[str] = None,
             reason: str = "on_demand") -> str:
        """Write the ring to ``path`` (default: a reason+pid-stamped
        file in ``dump_dir``) atomically: temp file in the target
        directory, fsync, then ``os.replace`` — a crash mid-dump
        leaves either the complete file or nothing, never a torn
        JSONL."""
        if path is None:
            step = self.last_step()
            name = (f"flightrec-{reason}-step{step if step is not None else 'NA'}"
                    f"-pid{os.getpid()}.jsonl")
            path = os.path.join(self.dump_dir, name)
        data = self.dump_bytes(reason)
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".flightrec-",
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        logger.warning("flight recorder dumped %d records to %s "
                       "(reason=%s)", len(self.tail()), path, reason)
        return path

    def _note_dump(self, reason: str) -> None:
        if self._dumps_total is not None:
            self._dumps_total.labels(reason=reason).inc()
        if self._last_dump_step is not None:
            step = self.last_step()
            self._last_dump_step.set(
                float(step) if step is not None else -1.0)


# -- process-global recorder (mirrors trace.get_tracer) ----------------
#
# Low-level seams (divergence guard, preemption handler, compile
# accounting, fit exception paths) reach the recorder through this
# global: None by default, so unconfigured runs pay one module-global
# read + None check per touchpoint.

_GLOBAL_RECORDER: Optional[FlightRecorder] = None


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _GLOBAL_RECORDER


def set_flight_recorder(
        rec: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    """Install ``rec`` as the process-global flight recorder and
    return the previous one (restore it when done — tests do)."""
    global _GLOBAL_RECORDER
    prev = _GLOBAL_RECORDER
    _GLOBAL_RECORDER = rec
    return prev


def record_event(kind: str, **attrs) -> None:
    """Event append on the global recorder, None-safe — the one-liner
    the guard/preemption/compile seams call."""
    rec = _GLOBAL_RECORDER
    if rec is not None:
        rec.event(kind, **attrs)


def dump_on_crash(reason: str) -> Optional[str]:
    """Best-effort dump of the global recorder — called from except
    paths that are about to re-raise, so it must never mask the
    original exception."""
    rec = _GLOBAL_RECORDER
    if rec is None or not rec.enabled:
        return None
    try:
        return rec.dump(reason=reason)
    except Exception:  # pragma: no cover - diagnostics must not mask
        logger.exception("flight recorder dump failed (reason=%s)",
                         reason)
        return None
