"""Hardware-truth profiling: HLO cost-model MFU accounting plus
per-step wall-time decomposition.

Two halves, both riding the PR-4 registry/tracer substrate:

**CostModel** — wraps ``jit(...).lower(...).compile().cost_analysis()``
into an immutable (flops, bytes accessed, arithmetic intensity) record
keyed by the same shape/kind identity ``CompileCache`` and the AOT
artifacts use (entry-point kind + transform-kind suffix + input shape
+ dtype — see ``compile/aot.py:artifact_fingerprint``). XLA's own
numbers for the program that actually runs, not an analytic estimate,
and deterministic per key: the same (kind, shape, dtype) always
resolves to the same cost. Combined with measured step wall time this
yields ``step_mfu`` / ``step_flops_per_sec`` / ``step_bytes_per_sec``
and a roofline classification, per engine step and per serving
bucket.

**StepProfiler** — per-step wall-time decomposition over the existing
seams:

- ``input_stall_ms``: ``PrefetchIterator`` consumer wait (how long the
  fit loop sat starved for the next batch);
- ``dispatch_ms``: ``AsyncDispatchWindow`` push block (waiting for a
  window slot, i.e. back-pressure from the device);
- ``device_ms``: device sync time observed at retirement
  (``jax.block_until_ready`` wall inside the window / score sync);
- ``host_ms``: everything else — Python bookkeeping plus listener
  callbacks (``TelemetryListener`` et al.; the listener share is also
  measured separately into each record as ``listener_ms``).

The four components sum to the measured step wall time by
construction (host is the remainder, clamped at 0 when a component
measured on another thread overlaps), exported as histograms and
traced as child spans of a per-step ``train.step`` span.

**Roofline classification** (gauge ``step_roofline_class``): a step is
``input_bound`` (3) when input stall exceeds ``input_bound_frac``
(default 25%) of wall; otherwise ``compute_bound`` (1) when the
executable's arithmetic intensity (flops / bytes accessed) is at or
above the machine balance (peak FLOP/s / peak bytes/s) and
``memory_bound`` (2) when below; ``unknown`` (0) when no peak is
known (CPU without the env override).

**Peak table**: dense bf16 peak FLOP/s lives in
``util/flops._PEAKS`` (keyed by TPU ``device_kind``); HBM bandwidth
per chip is tabled here. ``DL4J_TPU_PEAK_FLOPS`` and
``DL4J_TPU_PEAK_BYTES_PER_SEC`` override both so CPU CI (and any
machine the table doesn't know) still exercises the full MFU path
with a stated roofline.

Install with ``set_active_profiler(StepProfiler(...))`` — the fit
drivers, prefetch iterator, and dispatch window consult the
process-global at one attribute-read + None-check per touchpoint, so
uninstalled runs pay nothing and a ``StepProfiler(enabled=False)``
prices the fully-wired path at one branch per call (held to <= 1%
overhead in ``bench.py profiler_overhead``).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

ENV_PEAK_FLOPS = "DL4J_TPU_PEAK_FLOPS"
ENV_PEAK_BYTES = "DL4J_TPU_PEAK_BYTES_PER_SEC"

# HBM bandwidth (bytes/s) per chip by device_kind substring, public
# cloud specs; ordered, first hit wins (mirrors util/flops._PEAKS).
_HBM_BYTES_PER_SEC: Tuple[Tuple[str, float], ...] = (
    ("v6 lite", 1640e9),  # Trillium / v6e
    ("v6e", 1640e9),
    ("v5 lite", 819e9),   # v5e
    ("v5e", 819e9),
    ("v5p", 2765e9),
    ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)

# roofline classification gauge values
ROOFLINE_UNKNOWN = 0
ROOFLINE_COMPUTE = 1
ROOFLINE_MEMORY = 2
ROOFLINE_INPUT = 3
ROOFLINE_NAMES = {
    ROOFLINE_UNKNOWN: "unknown",
    ROOFLINE_COMPUTE: "compute_bound",
    ROOFLINE_MEMORY: "memory_bound",
    ROOFLINE_INPUT: "input_bound",
}


def peak_flops(device=None) -> Tuple[Optional[float], str]:
    """(peak FLOP/s, source) — the ``DL4J_TPU_PEAK_FLOPS`` env
    override when set (CPU CI states its own roofline), else the
    documented per-chip table in ``util/flops``. None off-TPU with no
    override: MFU is only defined against a known roofline."""
    env = os.environ.get(ENV_PEAK_FLOPS)
    if env:
        try:
            v = float(env)
            if v > 0:
                return v, "env"
        except ValueError:
            pass
    from deeplearning4j_tpu.util.flops import device_peak_flops

    return device_peak_flops(device)


def peak_bytes_per_sec(device=None) -> Tuple[Optional[float], str]:
    """(peak HBM bytes/s, source): env override, else the per-chip
    table, else None."""
    env = os.environ.get(ENV_PEAK_BYTES)
    if env:
        try:
            v = float(env)
            if v > 0:
                return v, "env"
        except ValueError:
            pass
    import jax

    d = device if device is not None else jax.devices()[0]
    kind = getattr(d, "device_kind", d.platform)
    if d.platform == "tpu":
        low = kind.lower()
        for key, bw in _HBM_BYTES_PER_SEC:
            if key in low:
                return bw, kind
    return None, kind


# -- cost model ---------------------------------------------------------


@dataclass(frozen=True)
class CostModel:
    """XLA's scheduled cost for ONE compiled executable: what the
    hardware was actually asked to do, keyed by the same shape/kind
    identity the compile cache and AOT artifacts use."""

    key: str
    flops: float
    bytes_accessed: float

    @property
    def arithmetic_intensity(self) -> float:
        """FLOPs per byte of memory traffic — the x-axis of the
        roofline plot."""
        return (self.flops / self.bytes_accessed
                if self.bytes_accessed else 0.0)

    def achieved(self, wall_s: float,
                 peak: Optional[float] = None) -> dict:
        """Achieved rates for one execution taking ``wall_s``
        seconds: flops_per_sec, bytes_per_sec, and mfu when a peak is
        known (else None)."""
        fps = self.flops / wall_s if wall_s > 0 else 0.0
        bps = self.bytes_accessed / wall_s if wall_s > 0 else 0.0
        return {
            "flops_per_sec": fps,
            "bytes_per_sec": bps,
            "mfu": (fps / peak) if peak else None,
        }

    def roofline_class(self, peak: Optional[float],
                       peak_bw: Optional[float]) -> int:
        """Compute- vs memory-bound from arithmetic intensity vs the
        machine balance point; unknown without a stated roofline.
        (Input-bound is a wall-time property, judged by the
        profiler, not the executable.)"""
        if not peak or not peak_bw or not self.bytes_accessed:
            return ROOFLINE_UNKNOWN
        balance = peak / peak_bw  # flops per byte at the ridge
        return (ROOFLINE_COMPUTE
                if self.arithmetic_intensity >= balance
                else ROOFLINE_MEMORY)

    @classmethod
    def from_cost_dict(cls, key: str, cost: dict) -> "CostModel":
        return cls(
            key=key,
            flops=float(cost.get("flops", 0.0)),
            # XLA spells it with a space; util/flops normalizes to _
            bytes_accessed=float(
                cost.get("bytes_accessed",
                         cost.get("bytes accessed", 0.0))),
        )

    @classmethod
    def from_jitted(cls, jitted, *args, key: str = "",
                    **kwargs) -> "CostModel":
        """Lower + compile an arbitrary jitted callable on concrete or
        abstract args and read XLA's cost analysis."""
        from deeplearning4j_tpu.util.flops import jit_cost

        return cls.from_cost_dict(key, jit_cost(jitted, *args,
                                                **kwargs))


def _shape_tag(shape) -> str:
    shape = tuple(shape)
    if shape and isinstance(shape[0], (tuple, list)):
        return ";".join("x".join(str(int(d)) for d in s)
                        for s in shape)
    return "x".join(str(int(d)) for d in shape)


def step_cost_key(model, batch_shape, dtype) -> str:
    """Cost-model identity of a train-step executable: entry-point
    kind + the transform-kind suffix (scan/remat/loss-scale/statguard/
    accum/zero/pallas change the HLO — same convention as the AOT
    artifact fingerprint) + input shape + dtype."""
    from deeplearning4j_tpu.nn.core import transform_kind_suffix

    return (f"step{transform_kind_suffix(model)}"
            f":{_shape_tag(batch_shape)}:{dtype}")


def output_cost_key(model, batch_shape, dtype) -> str:
    """Cost-model identity of an inference-forward executable (the
    serving bucket path) — mirrors the engine's AOT output kind."""
    kind = "output"
    fn = getattr(model, "_output_kind", None)
    if callable(fn):
        try:
            kind = fn()
        except Exception:
            pass
    return f"{kind}:{_shape_tag(batch_shape)}:{dtype}"


def kernel_cost_key(kernel: str, identity: dict,
                    config=None) -> str:
    """Cost-model identity of ONE Pallas kernel variant — the
    autotuner's prior records. Same spirit as ``step_cost_key``: the
    kernel kind plus the exact shape/dtype identity the tuning cache
    is keyed by, with the candidate block config appended when the
    record describes one specific tiling."""
    tag = ";".join(f"{k}={identity[k]}" for k in sorted(identity))
    key = f"kernel:{kernel}:{tag}"
    if config is not None:
        key += ":cfg=" + "x".join(str(int(v)) for v in config)
    return key


class CostModelCache:
    """Per-executable cost models, computed once per shape/kind key.

    The build (re-lower + compile) is host-side work that never
    touches the training trajectory; with the persistent XLA cache
    warm it is a cache read. Build failures are cached as None so a
    model that can't be lowered (stub models, exotic input
    marshalling) costs one attempt, not one per step."""

    def __init__(self):
        self._lock = threading.Lock()
        self._models: Dict[str, Optional[CostModel]] = {}

    def get_or_build(
            self, key: str,
            builder: Callable[[], Optional[CostModel]],
    ) -> Optional[CostModel]:
        with self._lock:
            if key in self._models:
                return self._models[key]
        try:
            cm = builder()
        except Exception:
            cm = None
        with self._lock:
            self._models.setdefault(key, cm)
            return self._models[key]

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {
                k: ({"flops": v.flops,
                     "bytes_accessed": v.bytes_accessed,
                     "arithmetic_intensity":
                         round(v.arithmetic_intensity, 3)}
                    if v is not None else None)
                for k, v in self._models.items()
            }


def train_step_cost_model(model, ds) -> Optional[CostModel]:
    """CostModel of ``model``'s own train-step executable on
    minibatch ``ds`` (the program ``fit_minibatch`` runs), keyed by
    step kind + shape + dtype."""
    import numpy as np

    from deeplearning4j_tpu.util.flops import train_step_cost

    feats = ds.features
    if isinstance(feats, (list, tuple)):
        shape = tuple(tuple(np.shape(f)) for f in feats
                      if f is not None)
        dtype = str(np.asarray(
            [f for f in feats if f is not None][0]).dtype)
    else:
        shape = tuple(np.shape(feats))
        dtype = str(np.asarray(feats).dtype)
    key = step_cost_key(model, shape, dtype)
    cost = train_step_cost(model, ds)
    return CostModel.from_cost_dict(key, cost)


def output_cost_model(model, batch_shape,
                      dtype="float32") -> Optional[CostModel]:
    """CostModel of the model's jitted inference forward for one
    padded bucket shape — computed off the request path (serving
    warmup), then looked up per dispatch."""
    import jax

    jitted = getattr(model, "_jit_output", None)
    if jitted is None or getattr(model, "params", None) is None:
        return None
    key = output_cost_key(model, batch_shape, dtype)
    x = jax.ShapeDtypeStruct(tuple(int(d) for d in batch_shape),
                             dtype)
    lowered = jitted.lower(model.params, model.state, x, None, None,
                           False)
    from deeplearning4j_tpu.util.flops import _cost_dict

    return CostModel.from_cost_dict(key, _cost_dict(lowered.compile()))


# -- step profiler ------------------------------------------------------

# decomposition histogram buckets: fine at the bottom (a healthy
# component is ~0) and coarse at the top, in ms (shared with the
# prefetch-wait idiom)
DECOMP_MS_BUCKETS = (0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                     100.0, 250.0, 1000.0)


class _StepState:
    __slots__ = ("step", "t0", "input_ms", "dispatch_ms", "device_ms",
                 "listener_ms", "span")

    def __init__(self, step, t0, span):
        self.step = step
        self.t0 = t0
        self.input_ms = 0.0
        self.dispatch_ms = 0.0
        self.device_ms = 0.0
        self.listener_ms = 0.0
        self.span = span


class StepProfiler:
    """Per-step MFU accounting + wall-time decomposition (module
    docstring has the full story). One instance per training run;
    install process-globally with ``set_active_profiler``."""

    def __init__(self, registry=None, tracer=None, recorder=None,
                 enabled: bool = True,
                 peak: Optional[float] = None,
                 peak_bw: Optional[float] = None,
                 input_bound_frac: float = 0.25,
                 clock: Callable[[], float] = time.perf_counter):
        from deeplearning4j_tpu.observability.metrics import (
            default_registry,
        )
        from deeplearning4j_tpu.observability.trace import get_tracer

        self.enabled = enabled
        self.registry = (registry if registry is not None
                         else default_registry())
        self.tracer = tracer if tracer is not None else get_tracer()
        self.recorder = recorder
        self.costs = CostModelCache()
        self.input_bound_frac = float(input_bound_frac)
        self._clock = clock
        if peak is None:
            peak, self.peak_source = peak_flops()
        else:
            self.peak_source = "caller"
        if peak_bw is None:
            peak_bw, self.peak_bw_source = peak_bytes_per_sec()
        else:
            self.peak_bw_source = "caller"
        self.peak = peak
        self.peak_bw = peak_bw
        self._state: Optional[_StepState] = None
        self._cost_memo = None  # (sig, CostModel) steady-state memo
        reg = self.registry
        self._h_input = reg.histogram(
            "training_input_stall_ms", buckets=DECOMP_MS_BUCKETS,
            help="step decomposition: fit loop starved for the next "
                 "batch (prefetch consumer wait)",
        )._default()
        self._h_host = reg.histogram(
            "training_host_ms", buckets=DECOMP_MS_BUCKETS,
            help="step decomposition: host-side remainder — Python "
                 "bookkeeping + listener callbacks",
        )._default()
        self._h_dispatch = reg.histogram(
            "training_dispatch_ms", buckets=DECOMP_MS_BUCKETS,
            help="step decomposition: blocked pushing into the async "
                 "dispatch window (device back-pressure)",
        )._default()
        self._h_device = reg.histogram(
            "training_device_ms", buckets=DECOMP_MS_BUCKETS,
            help="step decomposition: device sync observed at "
                 "retirement (block_until_ready / score sync)",
        )._default()
        self._g_mfu = reg.gauge(
            "step_mfu",
            help="profiler: achieved / peak FLOP/s of the last step "
                 "(cost-model flops over measured wall; requires a "
                 "known peak — DL4J_TPU_PEAK_FLOPS off-TPU)",
        )._default()
        self._g_fps = reg.gauge(
            "step_flops_per_sec",
            help="profiler: cost-model FLOPs / measured step wall",
        )._default()
        self._g_bps = reg.gauge(
            "step_bytes_per_sec",
            help="profiler: cost-model bytes accessed / measured "
                 "step wall",
        )._default()
        self._g_class = reg.gauge(
            "step_roofline_class",
            help="profiler: roofline classification of the last step "
                 "(0 unknown / 1 compute-bound / 2 memory-bound / "
                 "3 input-bound)",
        )._default()

    # -- hot-path hooks (called by the seams) ---------------------------

    def begin_step(self, step: int, parent=None) -> None:
        if not self.enabled:
            return
        span = None
        if self.tracer is not None and self.tracer.enabled:
            span = self.tracer.start_span(
                "train.step", parent=parent, attrs={"step": int(step)})
        self._state = _StepState(int(step), self._clock(), span)

    def note_input_wait_ms(self, ms: float) -> None:
        st = self._state
        if st is not None:
            st.input_ms += ms

    def note_dispatch_ms(self, ms: float) -> None:
        st = self._state
        if st is not None:
            st.dispatch_ms += ms

    def note_device_ms(self, ms: float) -> None:
        st = self._state
        if st is not None:
            st.device_ms += ms

    def note_listener_ms(self, ms: float) -> None:
        st = self._state
        if st is not None:
            st.listener_ms += ms

    # -- end of step ----------------------------------------------------

    def end_step(self, model=None, ds=None, score=None,
                 grad_norm=None, rows=None,
                 cost: Optional[CostModel] = None,
                 chunk: Optional[int] = None) -> Optional[dict]:
        """Close the current step: decompose wall time, publish the
        gauges/histograms, append the flight-recorder record, and end
        the per-step span (child spans per component). Returns the
        record dict (None when disabled / unpaired). ``chunk=K``
        marks a fused megastep record covering K optimizer steps
        under ONE dispatch (``step`` is then the LAST covered step) —
        recorder-measured dispatches/step over a run is
        records/steps, ~1/K under megastep."""
        st = self._state
        if not self.enabled or st is None:
            return None
        self._state = None
        wall_ms = (self._clock() - st.t0) * 1000.0
        measured = st.input_ms + st.dispatch_ms + st.device_ms
        host_ms = max(0.0, wall_ms - measured)
        self._h_input.observe(st.input_ms)
        self._h_host.observe(host_ms)
        self._h_dispatch.observe(st.dispatch_ms)
        self._h_device.observe(st.device_ms)

        if cost is None and model is not None and ds is not None:
            feats = ds.features
            # steady-state fast path: same model + batch geometry as
            # last step -> reuse the resolved cost without rebuilding
            # the shape/kind key (the key walk costs more than the
            # rest of this method together on a small step)
            try:
                # _jit_step identity doubles as knob invalidation:
                # scan/remat/accum flips rebuild the jitted step
                sig = (id(model), id(model._jit_step),
                       feats.shape, feats.dtype)
            except AttributeError:
                sig = None
            memo = self._cost_memo
            if sig is not None and memo is not None \
                    and memo[0] == sig:
                cost = memo[1]
            else:
                key = None
                try:
                    import numpy as np

                    if isinstance(feats, (list, tuple)):
                        shape = tuple(
                            tuple(np.shape(f)) for f in feats
                            if f is not None)
                        dtype = str(np.asarray(
                            [f for f in feats
                             if f is not None][0]).dtype)
                    else:
                        shape = tuple(np.shape(feats))
                        dtype = str(np.asarray(feats).dtype)
                    key = step_cost_key(model, shape, dtype)
                except Exception:
                    key = None
                if key is not None:
                    cost = self.costs.get_or_build(
                        key, lambda: train_step_cost_model(model, ds))
                if sig is not None:
                    self._cost_memo = (sig, cost)

        mfu = fps = bps = intensity = None
        klass = ROOFLINE_UNKNOWN
        if cost is not None:
            ach = cost.achieved(wall_ms / 1000.0, self.peak)
            fps, bps, mfu = (ach["flops_per_sec"],
                             ach["bytes_per_sec"], ach["mfu"])
            intensity = cost.arithmetic_intensity
            klass = cost.roofline_class(self.peak, self.peak_bw)
            self._g_fps.set(fps)
            self._g_bps.set(bps)
            if mfu is not None:
                self._g_mfu.set(mfu)
        if (wall_ms > 0
                and st.input_ms >= self.input_bound_frac * wall_ms):
            klass = ROOFLINE_INPUT
        self._g_class.set(float(klass))

        rec = {
            "step": st.step,
            "wall_ms": round(wall_ms, 3),
            "input_stall_ms": round(st.input_ms, 3),
            "host_ms": round(host_ms, 3),
            "dispatch_ms": round(st.dispatch_ms, 3),
            "device_ms": round(st.device_ms, 3),
            "listener_ms": round(st.listener_ms, 3),
            "roofline": ROOFLINE_NAMES[klass],
        }
        if score is not None:
            rec["loss"] = score
        if grad_norm is not None:
            rec["grad_norm"] = grad_norm
        if rows is not None:
            rec["rows"] = int(rows)
        if chunk is not None:
            rec["chunk"] = int(chunk)
        if cost is not None:
            rec["cost_key"] = cost.key
            if mfu is not None:
                rec["mfu"] = round(mfu, 6)
            rec["flops_per_sec"] = fps
            rec["arithmetic_intensity"] = (
                round(intensity, 3) if intensity is not None else None)

        span = st.span
        if span is not None:
            for name, ms in (("input", st.input_ms),
                             ("host", host_ms),
                             ("dispatch", st.dispatch_ms),
                             ("device", st.device_ms)):
                self.tracer.start_span(
                    f"train.step.{name}", parent=span,
                    attrs={"ms": round(ms, 3)},
                ).end()
            span.set_attr("wall_ms", round(wall_ms, 3))
            span.set_attr("roofline", ROOFLINE_NAMES[klass])
            rec["trace_id"] = span.context.trace_id
            span.end()
        if self.recorder is not None:
            self.recorder.record(**rec)
        return rec

    def abandon_step(self) -> None:
        """Drop an open step without recording (exception paths)."""
        st = self._state
        self._state = None
        if st is not None and st.span is not None:
            st.span.end("error")

    def snapshot(self) -> dict:
        """Bounded JSON view for /debugz."""
        return {
            "enabled": self.enabled,
            "peak_flops": self.peak,
            "peak_flops_source": self.peak_source,
            "peak_bytes_per_sec": self.peak_bw,
            "peak_bytes_source": self.peak_bw_source,
            "input_bound_frac": self.input_bound_frac,
            "cost_models": self.costs.snapshot(),
        }


# -- process-global profiler (mirrors trace.get_tracer) ----------------

_ACTIVE: Optional[StepProfiler] = None


def get_active_profiler() -> Optional[StepProfiler]:
    return _ACTIVE


def set_active_profiler(
        prof: Optional[StepProfiler]) -> Optional[StepProfiler]:
    """Install ``prof`` as the process-global step profiler (the fit
    drivers / prefetch / dispatch seams consult it) and return the
    previous one."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = prof
    return prev
