"""Unified observability subsystem: metrics registry, structured
tracing, exporters, and JAX runtime telemetry.

One substrate for every signal the runtime emits (the serving tier,
the training listeners, the resilience primitives, and the XLA
compile accounting all publish here):

- ``metrics.py`` — thread-safe ``MetricsRegistry`` with labeled
  ``Counter``/``Gauge``/``Histogram``/``Summary`` families, a no-op
  mode for overhead-free disablement, and the canonical
  ``Reservoir``/``Histogram`` primitives (re-exported by
  ``serving/metrics.py`` for back-compat);
- ``trace.py`` — ``Tracer``/``Span`` with deterministic seeded ids,
  explicit cross-thread context handoff, a bounded ``JsonlSink``,
  and a process-global tracer for low-level primitives;
- ``export.py`` — Prometheus text exposition
  (``/metrics?format=prometheus`` on the serving and UI servers) and
  JSON snapshots;
- ``runtime.py`` — JAX device memory gauges and the
  ``TelemetryListener`` publishing step time / loss / grad
  global-norm / examples-per-sec from both engines' fit loops;
- ``profiler.py`` — hardware-truth step profiling: per-executable
  ``CostModel`` from XLA cost analysis, MFU/roofline gauges, and the
  ``{input_stall, host, dispatch, device}`` wall-time decomposition;
- ``flightrec.py`` — the crash-dumping flight recorder: a bounded
  lock-free ring of step records + subsystem events with atomic
  JSONL dumps (guard trips, fit exceptions, preemption manifests).
"""

from deeplearning4j_tpu.observability.metrics import (  # noqa: F401
    Histogram,
    MetricsRegistry,
    NULL_REGISTRY,
    Reservoir,
    array_histograms,
    default_registry,
    mean_magnitudes,
)
from deeplearning4j_tpu.observability.trace import (  # noqa: F401
    JsonlSink,
    Span,
    SpanContext,
    Tracer,
    get_tracer,
    set_global_tracer,
)
from deeplearning4j_tpu.observability.export import (  # noqa: F401
    prometheus_text,
    registry_snapshot,
)
from deeplearning4j_tpu.observability.runtime import (  # noqa: F401
    TelemetryListener,
    device_memory_stats,
    publish_device_memory,
)
from deeplearning4j_tpu.observability.profiler import (  # noqa: F401
    CostModel,
    CostModelCache,
    StepProfiler,
    get_active_profiler,
    set_active_profiler,
)
from deeplearning4j_tpu.observability.flightrec import (  # noqa: F401
    FlightRecorder,
    get_flight_recorder,
    set_flight_recorder,
)
