"""Registry exporters: Prometheus text exposition + JSON snapshot.

The exposition format (text/plain; version=0.0.4) is the lingua
franca of scraping — emitting it from the serving and UI HTTP servers
means any standard collector can consume this runtime without an
adapter. JSON stays the default on both endpoints (existing tooling
parses it); ``?format=prometheus`` selects the text form.

Format rules implemented here (and asserted in
``tests/test_observability.py``):

- ``# HELP`` / ``# TYPE`` header per family (HELP only when a help
  string was registered; HELP text escapes ``\\`` and newline);
- label values escape backslash, double-quote, and newline;
- histograms emit CUMULATIVE ``_bucket{le="..."}`` series ending at
  ``le="+Inf"`` == ``_count``, plus ``_sum``;
- summaries emit ``{quantile="..."}`` series plus ``_sum``/``_count``
  (quantiles from the registry's reservoir — nearest-rank over the
  recent window, absent while the reservoir is empty).
"""

from __future__ import annotations

import math
from typing import Optional

from deeplearning4j_tpu.observability.metrics import (
    COUNTER,
    GAUGE,
    HISTOGRAM,
    SUMMARY,
    MetricsRegistry,
)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v) -> str:
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_str(names, values, extra=()) -> str:
    pairs = [
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    ]
    pairs += [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def prometheus_text(registry: MetricsRegistry) -> str:
    """Render every family in ``registry`` in exposition format."""
    lines = []
    for fam in registry.collect():
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for child in fam.children():
            ls = _labels_str(fam.label_names, child.label_values)
            if fam.kind in (COUNTER, GAUGE):
                lines.append(
                    f"{fam.name}{ls} {_fmt_value(child.value)}"
                )
            elif fam.kind == HISTOGRAM:
                for le, cum in child.cumulative():
                    lle = _labels_str(
                        fam.label_names, child.label_values,
                        extra=(("le", _fmt_value(le)),),
                    )
                    lines.append(f"{fam.name}_bucket{lle} {cum}")
                lines.append(
                    f"{fam.name}_sum{ls} {_fmt_value(child.total)}"
                )
                lines.append(f"{fam.name}_count{ls} {child.count}")
            elif fam.kind == SUMMARY:
                for q, v in child.quantile_values():
                    if v is None:
                        continue
                    lq = _labels_str(
                        fam.label_names, child.label_values,
                        extra=(("quantile", _fmt_value(q)),),
                    )
                    lines.append(f"{fam.name}{lq} {_fmt_value(v)}")
                lines.append(
                    f"{fam.name}_sum{ls} {_fmt_value(child.total)}"
                )
                lines.append(f"{fam.name}_count{ls} {child.count}")
    return "\n".join(lines) + "\n"


def registry_snapshot(registry: MetricsRegistry) -> dict:
    """JSON-able view: counters/gauges as scalars, histograms and
    summaries as their snapshot dicts; labeled families nest by
    joined label values."""
    out = {}
    for fam in registry.collect():
        def _one(child):
            if fam.kind in (COUNTER, GAUGE):
                return child.value
            return child.snapshot()

        if not fam.label_names:
            out[fam.name] = _one(fam.children()[0])
        else:
            out[fam.name] = {
                ",".join(c.label_values): _one(c)
                for c in fam.children()
            }
    return out


def parse_format_query(path: str) -> "tuple[str, Optional[str]]":
    """Split an HTTP request path into (route, format) where format
    is the ``format=`` query value (None when absent) — shared by the
    serving and UI handlers so both speak ``/metrics?format=...``."""
    from urllib.parse import parse_qs, urlparse

    url = urlparse(path)
    fmt = parse_qs(url.query).get("format", [None])[0]
    return url.path, fmt
