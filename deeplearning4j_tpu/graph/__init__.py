"""Graph embeddings module (reference ``deeplearning4j-graph`` —
SURVEY.md §2.7): adjacency-list graph, vectorized random walks,
DeepWalk with hierarchical softmax over a degree-based Huffman tree,
txt serialization."""

from deeplearning4j_tpu.graph.api import (
    Edge,
    NoEdgeHandling,
    NoEdgesException,
    ParseException,
    Vertex,
    VertexSequence,
)
from deeplearning4j_tpu.graph.deepwalk import (
    DeepWalk,
    GraphHuffman,
    GraphVectorsImpl,
    InMemoryGraphLookupTable,
)
from deeplearning4j_tpu.graph.graph import Graph, generate_random_walks
from deeplearning4j_tpu.graph.loader import (
    load_undirected_graph_edge_list_file,
    load_vertex_values,
    load_weighted_edge_list_file,
)
from deeplearning4j_tpu.graph.serializer import (
    load_txt_vectors,
    write_graph_vectors,
)
from deeplearning4j_tpu.graph.walks import (
    RandomWalkGraphIteratorProvider,
    RandomWalkIterator,
    WeightedRandomWalkGraphIteratorProvider,
    WeightedRandomWalkIterator,
)

__all__ = [
    "Edge", "NoEdgeHandling", "NoEdgesException", "ParseException",
    "Vertex", "VertexSequence", "DeepWalk", "GraphHuffman",
    "GraphVectorsImpl", "InMemoryGraphLookupTable", "Graph",
    "generate_random_walks", "load_undirected_graph_edge_list_file",
    "load_vertex_values", "load_weighted_edge_list_file",
    "load_txt_vectors", "write_graph_vectors",
    "RandomWalkGraphIteratorProvider", "RandomWalkIterator",
    "WeightedRandomWalkGraphIteratorProvider",
    "WeightedRandomWalkIterator",
]
