"""Graph primitives (reference ``deeplearning4j-graph``:
``graph/api/Vertex.java``, ``Edge.java``, ``IGraph.java``,
``NoEdgeHandling.java``, ``IVertexSequence.java``)."""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Generic, List, Optional, TypeVar

V = TypeVar("V")


class NoEdgeHandling(enum.Enum):
    """What a random walk does at a vertex with no (outgoing) edges
    (reference ``graph/api/NoEdgeHandling.java``)."""

    SELF_LOOP_ON_DISCONNECTED = "SELF_LOOP_ON_DISCONNECTED"
    EXCEPTION_ON_DISCONNECTED = "EXCEPTION_ON_DISCONNECTED"


class NoEdgesException(RuntimeError):
    """Walk hit a disconnected vertex under EXCEPTION_ON_DISCONNECTED
    (reference ``graph/exception/NoEdgesException.java``)."""


class ParseException(ValueError):
    """Malformed graph file line (reference
    ``graph/exception/ParseException.java``)."""


@dataclass(frozen=True)
class Vertex(Generic[V]):
    """A vertex: integer index + optional user value (reference
    ``graph/api/Vertex.java``)."""

    idx: int
    value: Optional[V] = None

    def vertex_id(self) -> int:
        return self.idx


@dataclass(frozen=True)
class Edge:
    """An edge, optionally directed and optionally weighted
    (reference ``graph/api/Edge.java`` — the generic edge value is a
    float weight here; unweighted edges carry weight 1.0)."""

    from_idx: int
    to_idx: int
    weight: float = 1.0
    directed: bool = False


class VertexSequence(Generic[V]):
    """A walk — sequence of vertices in a graph (reference
    ``graph/graph/VertexSequence.java``)."""

    def __init__(self, graph: Any, indices: List[int]):
        self._graph = graph
        self._indices = list(indices)

    def sequence_length(self) -> int:
        return len(self._indices)

    def indices(self) -> List[int]:
        return list(self._indices)

    def __iter__(self):
        for i in self._indices:
            yield self._graph.get_vertex(i)

    def __len__(self) -> int:
        return len(self._indices)
