"""Adjacency-list graph with a CSR view for vectorized walks
(reference ``graph/graph/Graph.java`` — same add-edge / degree /
random-neighbor API, but edges compile into CSR (offsets, targets,
weights) numpy arrays so that thousands of random walks are generated
in one vectorized sweep instead of per-step ``Random.nextInt`` calls).
"""

from __future__ import annotations

from typing import Generic, List, Optional, Sequence, TypeVar

import numpy as np

from deeplearning4j_tpu.graph.api import (
    Edge,
    NoEdgeHandling,
    NoEdgesException,
    Vertex,
)

V = TypeVar("V")


class Graph(Generic[V]):
    """Graph with vertices indexed 0..n-1 (reference
    ``graph/graph/Graph.java``). Undirected edges are stored in both
    adjacency lists, matching the reference's behavior."""

    def __init__(self, n_vertices: int, allow_multiple_edges: bool = False,
                 vertex_values: Optional[Sequence[V]] = None):
        if n_vertices <= 0:
            raise ValueError("n_vertices must be positive")
        self.n_vertices = n_vertices
        self.allow_multiple_edges = allow_multiple_edges
        self._values: List[Optional[V]] = (
            list(vertex_values) if vertex_values is not None
            else [None] * n_vertices
        )
        if len(self._values) != n_vertices:
            raise ValueError("vertex_values length != n_vertices")
        self._adj: List[List[Edge]] = [[] for _ in range(n_vertices)]
        self._csr = None  # (offsets, targets, weights), built lazily
        self._weighted_tables = None  # (cum, base, totals), built lazily

    # -- construction ---------------------------------------------------

    def add_edge(self, from_idx: int, to_idx: int, weight: float = 1.0,
                 directed: bool = False) -> None:
        if not (0 <= from_idx < self.n_vertices
                and 0 <= to_idx < self.n_vertices):
            raise ValueError(
                f"edge ({from_idx},{to_idx}) out of range for "
                f"{self.n_vertices} vertices"
            )
        if self.allow_multiple_edges:
            add_fwd = True
            add_rev = not directed and from_idx != to_idx
        else:
            # dedupe each direction independently, so an earlier
            # directed edge doesn't swallow a later undirected
            # request's reverse half
            add_fwd = not any(
                ex.to_idx == to_idx for ex in self._adj[from_idx]
            )
            add_rev = (
                not directed and from_idx != to_idx
                and not any(
                    ex.to_idx == from_idx for ex in self._adj[to_idx]
                )
            )
        if add_fwd:
            self._adj[from_idx].append(
                Edge(from_idx, to_idx, weight, directed)
            )
        if add_rev:
            self._adj[to_idx].append(Edge(to_idx, from_idx, weight, False))
        if add_fwd or add_rev:
            self._csr = None
            self._weighted_tables = None

    def add_edges(self, edges: Sequence[Edge]) -> None:
        for e in edges:
            self.add_edge(e.from_idx, e.to_idx, e.weight, e.directed)

    # -- queries --------------------------------------------------------

    def num_vertices(self) -> int:
        return self.n_vertices

    def get_vertex(self, idx: int) -> Vertex[V]:
        return Vertex(idx, self._values[idx])

    def get_vertex_degree(self, idx: int) -> int:
        return len(self._adj[idx])

    def get_edges_out(self, idx: int) -> List[Edge]:
        return list(self._adj[idx])

    def get_connected_vertex_indices(self, idx: int) -> np.ndarray:
        return np.asarray(
            [e.to_idx for e in self._adj[idx]], dtype=np.int32
        )

    def degrees(self) -> np.ndarray:
        return np.asarray(
            [len(a) for a in self._adj], dtype=np.int32
        )

    def random_connected_vertex(self, idx: int,
                                rng: np.random.RandomState) -> int:
        adj = self._adj[idx]
        if not adj:
            raise NoEdgesException(f"vertex {idx} has no edges")
        return adj[rng.randint(len(adj))].to_idx

    # -- CSR view for vectorized walks ----------------------------------

    def csr(self):
        """(offsets[n+1], targets[E], weights[E]) int32/int32/float32 —
        the flat neighbor table every vectorized walk indexes into."""
        if self._csr is None:
            deg = self.degrees()
            offsets = np.zeros(self.n_vertices + 1, np.int64)
            np.cumsum(deg, out=offsets[1:])
            targets = np.empty(int(offsets[-1]), np.int32)
            weights = np.empty(int(offsets[-1]), np.float32)
            for i, adj in enumerate(self._adj):
                s = int(offsets[i])
                for j, e in enumerate(adj):
                    targets[s + j] = e.to_idx
                    weights[s + j] = e.weight
            self._csr = (offsets, targets, weights)
        return self._csr

    def weighted_sampling_tables(self):
        """(cum[E], base[n], totals[n]) float64 inverse-CDF tables for
        weighted neighbor sampling; cached per graph."""
        if self._weighted_tables is None:
            offsets, _, weights = self.csr()
            cum = np.cumsum(weights.astype(np.float64))
            lo, hi = offsets[:-1], offsets[1:]
            base = np.where(lo > 0, cum[np.maximum(lo - 1, 0)], 0.0)
            totals = np.where(hi > lo, cum[np.maximum(hi - 1, 0)] - base,
                              0.0)
            self._weighted_tables = (cum, base, totals)
        return self._weighted_tables


def generate_random_walks(
    graph: Graph, walk_length: int, starts: np.ndarray, seed: int,
    mode: NoEdgeHandling = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
    weighted: bool = False,
) -> np.ndarray:
    """Vectorized batch walk generation: [len(starts), walk_length+1]
    int32. All walks advance one step per loop iteration via fancy
    indexing into the CSR table (the TPU-era replacement for the
    reference's per-walk ``RandomWalkIterator.next()`` /
    ``WeightedRandomWalkIterator.next()`` scalar loops).

    Disconnected vertices self-loop (SELF_LOOP_ON_DISCONNECTED) or
    raise (EXCEPTION_ON_DISCONNECTED), matching
    ``graph/api/NoEdgeHandling.java`` semantics."""
    offsets, targets, weights = graph.csr()
    deg = (offsets[1:] - offsets[:-1]).astype(np.int64)
    rng = np.random.RandomState(seed)
    n = len(starts)
    walks = np.empty((n, walk_length + 1), np.int32)
    walks[:, 0] = starts
    if walk_length == 0:
        return walks
    disconnected = deg == 0
    if mode is NoEdgeHandling.EXCEPTION_ON_DISCONNECTED and np.any(
        disconnected[starts]
    ):
        raise NoEdgesException(
            "walk started at a vertex with no edges "
            "(NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)"
        )
    if weighted:
        cum, base, totals = graph.weighted_sampling_tables()
    cur = starts.astype(np.int64)
    for step in range(1, walk_length + 1):
        d = deg[cur]
        has_edge = d > 0
        if mode is NoEdgeHandling.EXCEPTION_ON_DISCONNECTED and not np.all(
            has_edge
        ):
            raise NoEdgesException(
                "walk reached a vertex with no edges "
                "(NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)"
            )
        if weighted:
            u = rng.random_sample(n) * totals[cur] + base[cur]
            idx = np.searchsorted(cum, u, side="right")
            idx = np.minimum(idx, offsets[cur + 1] - 1)
            idx = np.maximum(idx, offsets[cur])
        else:
            # uniform neighbor choice; safe dummy for deg=0
            idx = offsets[cur] + (
                rng.random_sample(n) * np.maximum(d, 1)
            ).astype(np.int64)
        nxt = np.where(has_edge, targets[np.minimum(idx, len(targets) - 1)]
                       if len(targets) else cur, cur)
        walks[:, step] = nxt
        cur = nxt.astype(np.int64)
    return walks
