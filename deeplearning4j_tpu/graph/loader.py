"""Graph file loaders (reference ``graph/data/GraphLoader.java``,
``DelimitedEdgeLineProcessor.java``, ``WeightedEdgeLineProcessor.java``,
``DelimitedVertexLoader.java``)."""

from __future__ import annotations

from typing import List, Optional

from deeplearning4j_tpu.graph.api import Edge, ParseException
from deeplearning4j_tpu.graph.graph import Graph


def _parse_edge_line(line: str, delim: str, weighted: bool,
                     directed: bool) -> Optional[Edge]:
    line = line.strip()
    if not line or line.startswith("#") or line.startswith("//"):
        return None
    parts = [p for p in line.split(delim) if p != ""]
    want = 3 if weighted else 2
    if len(parts) != want:
        raise ParseException(
            f"expected {want} fields delimited by {delim!r}: {line!r}"
        )
    f, t = int(parts[0]), int(parts[1])
    w = float(parts[2]) if weighted else 1.0
    return Edge(f, t, w, directed)


def load_undirected_graph_edge_list_file(
    path: str, n_vertices: int, delim: str = ",",
) -> Graph:
    """Edge list "from,to" per line → undirected graph (reference
    ``GraphLoader.loadUndirectedGraphEdgeListFile``)."""
    return _load(path, n_vertices, delim, weighted=False, directed=False)


def load_weighted_edge_list_file(
    path: str, n_vertices: int, delim: str = ",", directed: bool = False,
) -> Graph:
    """Edge list "from,to,weight" per line (reference
    ``GraphLoader.loadWeightedEdgeListFile``)."""
    return _load(path, n_vertices, delim, weighted=True, directed=directed)


def _load(path, n_vertices, delim, weighted, directed) -> Graph:
    g = Graph(n_vertices)
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            e = _parse_edge_line(line, delim, weighted, directed)
            if e is not None:
                g.add_edge(e.from_idx, e.to_idx, e.weight, e.directed)
    return g


def load_vertex_values(path: str, delim: str = ":") -> List[str]:
    """"index<delim>value" lines → values ordered by index (reference
    ``DelimitedVertexLoader.java``)."""
    pairs = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            idx, _, val = line.partition(delim)
            if _ == "":
                raise ParseException(f"no delimiter {delim!r} in {line!r}")
            pairs.append((int(idx), val))
    pairs.sort()
    return [v for _, v in pairs]
