"""Save/load graph vectors (reference
``graph/models/loader/GraphVectorSerializer.java`` — tab-delimited
"index\\tv0\\tv1..." per line; loading reconstructs a query-only
GraphVectors)."""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.graph.deepwalk import (
    GraphVectorsImpl,
    InMemoryGraphLookupTable,
)

_DELIM = "\t"


def write_graph_vectors(model: GraphVectorsImpl, path: str) -> None:
    n = model.num_vertices()
    d = model.get_vector_size()
    with open(path, "w", encoding="utf-8") as f:
        for i in range(n):
            vec = model.get_vertex_vector(i)
            f.write(
                str(i) + _DELIM
                + _DELIM.join(repr(float(vec[j])) for j in range(d)) + "\n"
            )


def load_txt_vectors(path: str) -> GraphVectorsImpl:
    from deeplearning4j_tpu.graph.api import ParseException

    rows = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split(_DELIM)
            if len(parts) < 2:
                continue
            rows.append((int(parts[0]), [float(x) for x in parts[1:]]))
    if not rows:
        raise ParseException(f"no vector lines found in {path!r}")
    if len({len(v) for _, v in rows}) != 1:
        raise ParseException(f"ragged vector lengths in {path!r}")
    rows.sort()
    vectors = np.asarray([v for _, v in rows], np.float32)
    table = InMemoryGraphLookupTable(
        vectors.shape[0], vectors.shape[1], tree=None, learning_rate=0.01
    )
    table.vertex_vectors = vectors
    return GraphVectorsImpl(table)
