"""DeepWalk graph embeddings (reference
``graph/models/deepwalk/DeepWalk.java``, ``GraphHuffman.java``,
``graph/models/embeddings/InMemoryGraphLookupTable.java``,
``GraphVectorsImpl.java``).

TPU-first redesign: the reference trains per (vertex, context) pair —
``lookupTable.iterate(first, second)`` does dot/sigmoid/axpy on one
row at a time across N racing threads. Here every epoch's walks are
generated in one vectorized sweep, skip-gram pairs are extracted with
numpy slicing, and ONE jitted XLA program per batch does
gather → dot → sigmoid → scatter-add over the hierarchical-softmax
paths (padded to fixed length, so it compiles once). Updates within a
batch are averaged — synchronous large-batch SGD; parity with the
reference's racing per-pair updates is statistical, as with Word2Vec
(SURVEY.md §7 hard part 3).
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.graph.api import NoEdgeHandling
from deeplearning4j_tpu.graph.graph import Graph, generate_random_walks
from deeplearning4j_tpu.nlp.vocab import Huffman, VocabWord


class GraphHuffman:
    """Huffman tree over vertex degrees for hierarchical softmax
    (reference ``GraphHuffman.java`` — degree plays the role word
    frequency plays in word2vec). Wraps the shared Huffman builder and
    exposes fixed-shape padded (codes, points, lengths) arrays for the
    jitted step."""

    def __init__(self, vertex_degrees: np.ndarray):
        words = [
            VocabWord(str(i), max(int(d), 1), i)
            for i, d in enumerate(vertex_degrees)
        ]
        h = Huffman(words)
        h.build()
        self._words = words
        self.codes, self.points, self.lengths = h.padded_arrays()


    def get_code(self, vertex: int) -> List[int]:
        return list(self._words[vertex].code)

    def get_code_length(self, vertex: int) -> int:
        return int(self.lengths[vertex])

    def get_path_inner_nodes(self, vertex: int) -> List[int]:
        return list(self._words[vertex].points)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _hs_graph_step(syn0, syn1, centers, codes, points, path_mask, alpha):
    """Batched HS update with the GRAPH sign convention (reference
    ``InMemoryGraphLookupTable.vectorsAndGradients``: per inner node,
    d(loss)/d(dot) = sigmoid(dot) - bit): loss per node is
    -log sigmoid((2·bit − 1) · (v_center · syn1[point]))."""

    def loss_fn(tables):
        s0, s1 = tables
        v = s0[centers]                      # [B, D]
        u = s1[points]                       # [B, L, D]
        x = jnp.einsum("bd,bld->bl", v, u)
        sign = 2.0 * codes - 1.0
        logp = jax.nn.log_sigmoid(sign * x)
        return -jnp.sum(path_mask * logp) / jnp.maximum(
            jnp.sum(jnp.any(path_mask > 0, axis=1)), 1.0
        )

    loss, (g0, g1) = jax.value_and_grad(loss_fn)((syn0, syn1))
    return syn0 - alpha * g0, syn1 - alpha * g1, loss


class InMemoryGraphLookupTable:
    """vertex_vectors [n, d] ('input') + out_weights [n-1, d] (inner
    binary-tree nodes) (reference ``InMemoryGraphLookupTable.java``).
    ``iterate``/``vectors_and_gradients`` keep the reference's
    single-pair contract (used by gradient-check tests); training goes
    through the batched jitted step."""

    def __init__(self, n_vertices: int, vector_size: int,
                 tree: Optional[GraphHuffman], learning_rate: float,
                 seed: int = 12345):
        self.n_vertices = n_vertices
        self._vector_size = vector_size
        self.tree = tree
        self.learning_rate = learning_rate
        rng = np.random.RandomState(seed)
        # Tables start as host arrays (the per-pair iterate path mutates
        # rows in place); batch_update promotes them to device-resident
        # jnp arrays and keeps them there across batches — no full-table
        # host<->device round-trip per step.
        self.vertex_vectors = (
            (rng.rand(n_vertices, vector_size) - 0.5) / vector_size
        ).astype(np.float32)
        self.out_weights = (
            (rng.rand(max(n_vertices - 1, 1), vector_size) - 0.5)
            / vector_size
        ).astype(np.float32)

    def vector_size(self) -> int:
        return self._vector_size

    def get_vertex_vectors(self) -> np.ndarray:
        return np.asarray(self.vertex_vectors)

    def set_learning_rate(self, lr: float) -> None:
        self.learning_rate = lr

    def get_vector(self, idx: int) -> np.ndarray:
        return np.asarray(self.vertex_vectors[idx])

    @staticmethod
    def _sigmoid(x: float) -> float:
        return 1.0 / (1.0 + np.exp(-x))

    def vectors_and_gradients(self, first: int, second: int):
        """(vectors, gradients) lists: entry 0 is the input vertex
        vector + its accumulated gradient; entries i>0 are the inner
        nodes on ``second``'s path + their gradients (reference
        ``InMemoryGraphLookupTable.vectorsAndGradients`` — same
        contract, kept for numerical gradient checks)."""
        v = np.asarray(self.vertex_vectors[first])
        bits = self.tree.get_code(second)
        inner = self.tree.get_path_inner_nodes(second)
        vecs = [v]
        grads = [np.zeros_like(v)]
        for bit, node in zip(bits, inner):
            u = np.asarray(self.out_weights[node])
            s = self._sigmoid(float(np.dot(u, v)))
            grads.append(v * (s - bit))
            grads[0] = grads[0] + (s - bit) * u
            vecs.append(u)
        return vecs, grads

    def _set_row(self, attr: str, idx: int, value: np.ndarray) -> None:
        table = getattr(self, attr)
        if isinstance(table, np.ndarray):
            table[idx] = value
        else:  # device-resident jnp table
            setattr(self, attr, table.at[idx].set(value))

    def iterate(self, first: int, second: int) -> None:
        """Single-pair SGD update (reference ``iterate``)."""
        vecs, grads = self.vectors_and_gradients(first, second)
        inner = self.tree.get_path_inner_nodes(second)
        self._set_row("vertex_vectors", first,
                      vecs[0] - self.learning_rate * grads[0])
        for i, node in enumerate(inner):
            self._set_row("out_weights", node,
                          vecs[i + 1] - self.learning_rate * grads[i + 1])

    def batch_update(self, centers: np.ndarray, contexts: np.ndarray,
                     alpha: float) -> float:
        """Batched HS update for pairs (centers→contexts) in one jitted
        step; returns mean loss."""
        codes = self.tree.codes[contexts]
        points = self.tree.points[contexts]
        L = self.tree.codes.shape[1]
        pmask = (
            np.arange(L)[None, :] < self.tree.lengths[contexts][:, None]
        ).astype(np.float32)
        # Promote once; afterwards the tables stay on device across
        # batches (the jitted step donates its inputs).
        s0 = jnp.asarray(self.vertex_vectors, jnp.float32)
        s1 = jnp.asarray(self.out_weights, jnp.float32)
        self.vertex_vectors, self.out_weights, loss = _hs_graph_step(
            s0, s1,
            jnp.asarray(centers, jnp.int32), jnp.asarray(codes),
            jnp.asarray(points, jnp.int32), jnp.asarray(pmask),
            jnp.float32(alpha),
        )
        return float(loss)


class GraphVectorsImpl:
    """Query API over learned vertex vectors (reference
    ``GraphVectorsImpl.java``): similarity, nearest vertices."""

    def __init__(self, lookup_table: Optional[InMemoryGraphLookupTable]
                 = None):
        self.lookup_table = lookup_table

    def num_vertices(self) -> int:
        return self.lookup_table.n_vertices

    def get_vector_size(self) -> int:
        return self.lookup_table.vector_size()

    def get_vertex_vector(self, idx: int) -> np.ndarray:
        return self.lookup_table.get_vector(idx)

    def similarity(self, a: int, b: int) -> float:
        va = self.get_vertex_vector(a)
        vb = self.get_vertex_vector(b)
        denom = float(np.linalg.norm(va) * np.linalg.norm(vb))
        return float(np.dot(va, vb) / denom) if denom > 0 else 0.0

    def vertices_nearest(self, idx: int, top: int = 10) -> List[int]:
        vecs = self.lookup_table.get_vertex_vectors()
        norms = np.linalg.norm(vecs, axis=1)
        norms = np.where(norms == 0, 1.0, norms)
        sims = (vecs @ vecs[idx]) / (norms * norms[idx])
        sims[idx] = -np.inf
        order = np.argsort(-sims)
        return order[:top].tolist()


class DeepWalk(GraphVectorsImpl):
    """DeepWalk (Perozzi, Al-Rfou & Skiena 2014) — unsupervised vertex
    embeddings from random walks, trained skip-gram-style with
    hierarchical softmax (reference ``DeepWalk.java``; its thread pool
    is replaced by batched walk generation + one jitted update per
    batch)."""

    STATUS_UPDATE_FREQUENCY = 1000

    def __init__(self, vector_size: int = 100, window_size: int = 2,
                 learning_rate: float = 0.01, seed: int = 12345,
                 batch_size: int = 2048):
        super().__init__(None)
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.seed = seed
        self.batch_size = batch_size
        self._init_called = False

    # -- lifecycle ------------------------------------------------------

    def initialize(self, graph_or_degrees) -> None:
        """Build the degree-based Huffman tree + lookup table
        (reference ``DeepWalk.initialize``)."""
        if isinstance(graph_or_degrees, Graph):
            degrees = graph_or_degrees.degrees()
        else:
            degrees = np.asarray(graph_or_degrees, np.int64)
        tree = GraphHuffman(degrees)
        self.lookup_table = InMemoryGraphLookupTable(
            len(degrees), self.vector_size, tree, self.learning_rate,
            seed=self.seed,
        )
        self._init_called = True

    def set_learning_rate(self, lr: float) -> None:
        self.learning_rate = lr
        if self.lookup_table is not None:
            self.lookup_table.set_learning_rate(lr)

    # -- training -------------------------------------------------------

    def _pairs_from_walks(self, walks: np.ndarray):
        """Vectorized skip-gram pair extraction (reference
        ``DeepWalk.skipGram``: centers mid ∈ [window, len-window), all
        offsets ±window)."""
        W, L = walks.shape
        w = self.window_size
        cs, xs = [], []
        for mid in range(w, L - w):
            for pos in range(mid - w, mid + w + 1):
                if pos == mid:
                    continue
                cs.append(walks[:, mid])
                xs.append(walks[:, pos])
        if not cs:
            return (np.empty(0, np.int32),) * 2
        return (
            np.concatenate(cs).astype(np.int32),
            np.concatenate(xs).astype(np.int32),
        )

    def fit(self, graph: Graph, walk_length: int = 8,
            epochs: int = 1) -> None:
        """Generate one walk per vertex per epoch (uniform random,
        self-loop on disconnected — reference ``DeepWalk.fit(IGraph,
        int)``) and train on all resulting skip-gram pairs."""
        if not self._init_called:
            self.initialize(graph)
        n = graph.num_vertices()
        for epoch in range(epochs):
            rng = np.random.RandomState(self.seed + epoch)
            starts = np.arange(n, dtype=np.int32)
            rng.shuffle(starts)
            walks = generate_random_walks(
                graph, walk_length, starts,
                seed=self.seed + 31 * epoch + 1,
                mode=NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
            )
            self.fit_walks(walks)

    def fit_walks(self, walks: np.ndarray) -> float:
        """Train on a precomputed [n_walks, L+1] walk batch (the fast
        path ``fit_iterator`` and ``fit`` feed)."""
        if not self._init_called:
            raise RuntimeError(
                "DeepWalk not initialized (call initialize before fit)"
            )
        centers, contexts = self._pairs_from_walks(walks)
        if len(centers) == 0:
            raise ValueError(
                f"no skip-gram pairs: walk has {walks.shape[1]} vertices "
                f"but window_size={self.window_size} needs walks of at "
                f"least {2 * self.window_size + 1} (walk_length >= "
                f"{2 * self.window_size})"
            )
        # shuffle pairs so batches mix walk positions
        perm = np.random.RandomState(self.seed ^ 0x5EED).permutation(
            len(centers)
        )
        centers, contexts = centers[perm], contexts[perm]
        # clamp the batch to the pair count, then tile up to a full
        # multiple of B so every pair trains (small graphs produce far
        # fewer pairs than the default batch size)
        B = min(self.batch_size, len(centers))
        n_full = -(-len(centers) // B) * B
        centers = np.resize(centers, n_full)
        contexts = np.resize(contexts, n_full)
        total = 0.0
        nb = len(centers) // B
        for i in range(nb):
            total += self.lookup_table.batch_update(
                centers[i * B:(i + 1) * B], contexts[i * B:(i + 1) * B],
                self.learning_rate,
            )
        return total / max(nb, 1)

    def fit_iterator(self, iterator) -> None:
        """Train from a GraphWalkIterator (reference
        ``DeepWalk.fit(GraphWalkIterator)``); uses the iterator's
        batched walk array when available."""
        if not self._init_called:
            raise RuntimeError(
                "DeepWalk not initialized (call initialize before fit)"
            )
        if hasattr(iterator, "walks_array"):
            self.fit_walks(iterator.walks_array())
            while iterator.has_next():  # mark consumed
                iterator.next()
            return
        seqs = []
        while iterator.has_next():
            seqs.append(iterator.next().indices())
        if seqs:
            self.fit_walks(np.asarray(seqs, np.int32))

    # -- builder --------------------------------------------------------

    class Builder:
        """Reference ``DeepWalk.Builder`` (vectorSize/seed/
        learningRate/windowSize)."""

        def __init__(self):
            self._vector_size = 100
            self._seed = 12345
            self._learning_rate = 0.01
            self._window_size = 2
            self._batch_size = 2048

        def vector_size(self, n): self._vector_size = n; return self
        def seed(self, n): self._seed = n; return self
        def learning_rate(self, x): self._learning_rate = x; return self
        def window_size(self, n): self._window_size = n; return self
        def batch_size(self, n): self._batch_size = n; return self

        def build(self) -> "DeepWalk":
            return DeepWalk(
                vector_size=self._vector_size, seed=self._seed,
                learning_rate=self._learning_rate,
                window_size=self._window_size,
                batch_size=self._batch_size,
            )
