"""Walk iterators (reference ``graph/iterator/RandomWalkIterator.java``,
``WeightedRandomWalkIterator.java``,
``graph/iterator/parallel/RandomWalkGraphIteratorProvider.java``).

Semantics preserved from the reference: one walk starts at every
vertex exactly once per epoch, starting order shuffled; walk of
length L contains L+1 vertices. Generation is batched (one vectorized
sweep fills every walk) — iteration just yields rows."""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.graph.api import (
    NoEdgeHandling,
    VertexSequence,
)
from deeplearning4j_tpu.graph.graph import Graph, generate_random_walks


class RandomWalkIterator:
    """Uniform random walks, one starting at each vertex of
    [first_vertex, last_vertex), order randomized (reference
    ``RandomWalkIterator.java``)."""

    weighted = False

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 mode: NoEdgeHandling =
                 NoEdgeHandling.EXCEPTION_ON_DISCONNECTED,
                 first_vertex: int = 0,
                 last_vertex: Optional[int] = None):
        self.graph = graph
        self._walk_length = walk_length
        self.seed = seed
        self.mode = mode
        self.first_vertex = first_vertex
        self.last_vertex = (
            last_vertex if last_vertex is not None else graph.num_vertices()
        )
        self._epoch = 0
        self.reset()

    def walk_length(self) -> int:
        return self._walk_length

    def reset(self) -> None:
        rng = np.random.RandomState(
            (self.seed + 7919 * self._epoch) & 0x7FFFFFFF
        )
        starts = np.arange(self.first_vertex, self.last_vertex,
                           dtype=np.int32)
        rng.shuffle(starts)
        self._walks = generate_random_walks(
            self.graph, self._walk_length, starts,
            seed=(self.seed + 104729 * self._epoch + 1) & 0x7FFFFFFF,
            mode=self.mode, weighted=self.weighted,
        )
        self._pos = 0
        self._epoch += 1

    def has_next(self) -> bool:
        return self._pos < len(self._walks)

    def next(self) -> VertexSequence:
        seq = VertexSequence(self.graph, self._walks[self._pos].tolist())
        self._pos += 1
        return seq

    def __iter__(self) -> Iterator[VertexSequence]:
        while self.has_next():
            yield self.next()

    def walks_array(self) -> np.ndarray:
        """The full [n_walks, L+1] int32 batch — the fast path DeepWalk
        trains from directly."""
        return self._walks


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional neighbor choice (reference
    ``WeightedRandomWalkIterator.java``)."""

    weighted = True


class RandomWalkGraphIteratorProvider:
    """Splits the vertex range into n roughly equal sub-ranges, one
    iterator each (reference
    ``RandomWalkGraphIteratorProvider.java``). With batched training
    the split exists for API parity and sharded walk generation."""

    iterator_cls = RandomWalkIterator

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 mode: NoEdgeHandling =
                 NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.mode = mode

    def get_graph_walk_iterators(self, n: int) -> List[RandomWalkIterator]:
        nv = self.graph.num_vertices()
        n = max(1, min(n, nv))
        bounds = np.linspace(0, nv, n + 1, dtype=np.int64)
        return [
            self.iterator_cls(
                self.graph, self.walk_length, seed=self.seed + i,
                mode=self.mode, first_vertex=int(bounds[i]),
                last_vertex=int(bounds[i + 1]),
            )
            for i in range(n)
            if bounds[i] < bounds[i + 1]
        ]


class WeightedRandomWalkGraphIteratorProvider(
    RandomWalkGraphIteratorProvider
):
    """Weighted variant (reference
    ``WeightedRandomWalkGraphIteratorProvider.java``)."""

    iterator_cls = WeightedRandomWalkIterator
