"""``ShardedDeepWalk``: DeepWalk vertex embeddings on mesh-row-sharded
tables.

``graph/deepwalk.py`` already trains batched + jitted, but its vertex
vectors and inner-node weights are dense device arrays — one device
must hold the whole graph's ``[V, D]`` (twice). Here both tables
become :class:`ShardedEmbeddingTable` shards and each batch runs the
fused hierarchical-softmax step from ``embeddings/table.py``
(collective lookup of the centers + path inner nodes, gradient w.r.t.
the gathered rows only, dedup + owner scatter) — same graph sign
convention and batch-averaged loss as the base ``_hs_graph_step``, so
trajectories agree to numerical parity while per-device residency
drops to ~1/N.

Eligibility fallback: the reference's single-pair ``iterate`` /
``vectors_and_gradients`` contract (used by gradient-check tests)
mutates host rows in place — that does not compose with row-sharded
device storage, so those methods raise loudly here; use the base
``InMemoryGraphLookupTable`` for per-pair work.

Persistence is canonical host rows + vertex degrees (the Huffman tree
rebuilds deterministically from degrees): ``save`` gathers, ``restore``
re-shards onto whatever mesh is present — train on 8 devices, resume
on 1, bitwise. ``fit`` continues the per-epoch walk seeds across
calls (``_epochs_done``), so a resumed run draws the walks the dead
run never got to, instead of replaying epoch 0.
"""

from __future__ import annotations

import io
import os

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.embeddings.table import (
    ShardedEmbeddingTable,
    _build_hs_graph_step,
    note_rows_touched,
)
from deeplearning4j_tpu.graph.deepwalk import (
    DeepWalk,
    GraphHuffman,
    InMemoryGraphLookupTable,
)
from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.parallel.mesh import build_mesh

_FORMAT = "sharded-deepwalk-v1"


class ShardedGraphLookupTable(InMemoryGraphLookupTable):
    """Graph lookup table whose vertex vectors and inner-node weights
    are row-sharded over the mesh. Initial rows come from the same RNG
    stream (same draw order) as the base class, so weights start
    bitwise identical."""

    def __init__(self, n_vertices: int, vector_size: int, tree,
                 learning_rate: float, seed: int = 12345, mesh=None):
        # No super().__init__: it allocates the dense host tables.
        self.n_vertices = n_vertices
        self._vector_size = vector_size
        self.tree = tree
        self.learning_rate = learning_rate
        self.mesh = mesh if mesh is not None else build_mesh()
        rng = np.random.RandomState(seed)
        rows0 = (
            (rng.rand(n_vertices, vector_size) - 0.5) / vector_size
        ).astype(np.float32)
        rows1 = (
            (rng.rand(max(n_vertices - 1, 1), vector_size) - 0.5)
            / vector_size
        ).astype(np.float32)
        self.t0 = ShardedEmbeddingTable.from_rows(rows0, mesh=self.mesh)
        self.t1 = ShardedEmbeddingTable.from_rows(rows1, mesh=self.mesh)

    # base-class names resolve to the raw sharded device arrays
    @property
    def vertex_vectors(self):
        return self.t0.table

    @property
    def out_weights(self):
        return self.t1.table

    def get_vertex_vectors(self) -> np.ndarray:
        # canonical unpadded rows (the raw array carries vocab padding)
        return self.t0.to_host()

    def get_vector(self, idx: int) -> np.ndarray:
        return np.asarray(self.t0.lookup(np.array([idx], np.int32))[0])

    def vectors_and_gradients(self, first: int, second: int):
        raise NotImplementedError(
            "per-pair vectors_and_gradients mutates host rows in place "
            "and does not compose with row-sharded tables; use the "
            "dense InMemoryGraphLookupTable for gradient checks"
        )

    def iterate(self, first: int, second: int) -> None:
        raise NotImplementedError(
            "per-pair iterate does not compose with row-sharded "
            "tables; train through batch_update"
        )

    def batch_update(self, centers: np.ndarray, contexts: np.ndarray,
                     alpha: float) -> float:
        """Same contract as the base: one fused jitted HS step for the
        (centers -> contexts) pair batch, returns mean loss — but the
        step is the sharded collective-lookup/owner-scatter program."""
        codes = self.tree.codes[contexts]
        points = self.tree.points[contexts]
        L = self.tree.codes.shape[1]
        pmask = (
            np.arange(L)[None, :] < self.tree.lengths[contexts][:, None]
        ).astype(np.float32)
        step_fn = _build_hs_graph_step(self.mesh)
        self.t0.table, self.t1.table, loss, touched = step_fn(
            self.t0.table, self.t1.table,
            jnp.asarray(centers, jnp.int32),
            jnp.asarray(codes, jnp.float32),
            jnp.asarray(points, jnp.int32),
            jnp.asarray(pmask),
            jnp.float32(alpha),
        )
        note_rows_touched(int(touched))
        return float(loss)


class ShardedDeepWalk(DeepWalk):
    """DeepWalk whose tables shard over the mesh's data axis. Same
    builder surface as :class:`DeepWalk` plus ``mesh``; adds
    ``save``/``restore`` (canonical rows, any-mesh restore) and
    continues epoch walk seeds across ``fit`` calls for resume."""

    def __init__(self, vector_size: int = 100, window_size: int = 2,
                 learning_rate: float = 0.01, seed: int = 12345,
                 batch_size: int = 2048, mesh=None):
        super().__init__(vector_size=vector_size,
                         window_size=window_size,
                         learning_rate=learning_rate, seed=seed,
                         batch_size=batch_size)
        self.mesh = mesh if mesh is not None else build_mesh()
        self._epochs_done = 0
        self._degrees = None

    def initialize(self, graph_or_degrees) -> None:
        if isinstance(graph_or_degrees, Graph):
            degrees = graph_or_degrees.degrees()
        else:
            degrees = np.asarray(graph_or_degrees, np.int64)
        self._degrees = np.asarray(degrees, np.int64)
        tree = GraphHuffman(degrees)
        self.lookup_table = ShardedGraphLookupTable(
            len(degrees), self.vector_size, tree, self.learning_rate,
            seed=self.seed, mesh=self.mesh,
        )
        self._init_called = True

    def fit(self, graph: Graph, walk_length: int = 8,
            epochs: int = 1) -> None:
        """Like the base fit, but epoch seeds continue across calls
        (``seed + epochs_done``, ...): fit(e1) then fit(e2) — on this
        instance or on one restored from its checkpoint — walks the
        same ground as a single fit(e1+e2)."""
        if not self._init_called:
            self.initialize(graph)
        from deeplearning4j_tpu.graph.api import NoEdgeHandling
        from deeplearning4j_tpu.graph.graph import generate_random_walks

        n = graph.num_vertices()
        first = self._epochs_done
        for epoch in range(first, first + epochs):
            rng = np.random.RandomState(self.seed + epoch)
            starts = np.arange(n, dtype=np.int32)
            rng.shuffle(starts)
            walks = generate_random_walks(
                graph, walk_length, starts,
                seed=self.seed + 31 * epoch + 1,
                mode=NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
            )
            self.fit_walks(walks)
            self._epochs_done = epoch + 1

    # -- persistence -----------------------------------------------------

    def save(self, path: str) -> None:
        """Canonical host rows + degrees + epoch counter, written
        atomically; restores onto a mesh of any width bitwise."""
        from deeplearning4j_tpu.resilience.checkpoint import (
            atomic_write_bytes,
        )

        if not self._init_called:
            raise RuntimeError("nothing to save: not initialized")
        lt = self.lookup_table
        buf = io.BytesIO()
        np.savez(
            buf,
            format=_FORMAT,
            vertex_vectors=lt.t0.to_host(),
            out_weights=lt.t1.to_host(),
            degrees=self._degrees,
            epochs_done=self._epochs_done,
            meta=np.array([self.vector_size, self.window_size,
                           self.seed, self.batch_size], np.int64),
        )
        atomic_write_bytes(os.fspath(path), buf.getvalue())

    def restore(self, path: str) -> None:
        """Rebuild the Huffman tree from the checkpoint's degrees and
        place its rows onto THIS instance's mesh."""
        with np.load(path, allow_pickle=False) as z:
            if str(z["format"]) != _FORMAT:
                raise ValueError(f"not a {_FORMAT} checkpoint: {path}")
            meta = z["meta"]
            want = np.array([self.vector_size, self.window_size,
                             self.seed, self.batch_size], np.int64)
            if not np.array_equal(meta, want):
                raise ValueError(
                    f"checkpoint hyperparameters {meta.tolist()} do "
                    f"not match this trainer's {want.tolist()} "
                    "(vector/window/seed/batch)"
                )
            self.initialize(z["degrees"])
            self.lookup_table.t0.restore_rows(z["vertex_vectors"])
            self.lookup_table.t1.restore_rows(z["out_weights"])
            self._epochs_done = int(z["epochs_done"])
