"""``ShardedEmbeddingTable``: embedding rows sharded ``P("data",
None)`` over the mesh, with collective lookup and owner-only sparse
scatter-add.

Sharding shape (ROADMAP "sharded embeddings": a genuinely different
shape than ZeRO's flat elementwise math): the ``[V, D]`` table is
row-partitioned over the mesh's ``data`` axis — device ``i`` of ``N``
holds rows ``[i*V/N, (i+1)*V/N)`` and nothing else, so the largest
table grows with the mesh instead of being bounded by one device's
HBM (``embedding_shard_bytes`` gauges the per-device residency,
~1/N of a replicated table).

- **Lookup** gathers only OWNED rows per shard (out-of-shard ids
  produce exact zeros) and exchanges via one ``psum`` — every term but
  the owner's contributes ``+0.0``, so the result is bitwise equal to
  an unsharded gather, on any mesh width.
- **Update** applies the deduped row gradients from
  ``embeddings/sparse.py`` owner-side only: each unique row is
  rewritten exactly once, by the shard that owns it, from replicated
  (mesh-width-independent) gradient math — which is what makes a run
  checkpointed on an 8-wide mesh resume bitwise on a 1-wide one.

This module is the package's ONE collective site: the raw
``psum``/``shard_map`` calls the ``scripts/lint_parity.py``
collective-locality rule admits for ``embeddings/`` all live here —
the Word2Vec/DeepWalk workloads compose the fused steps below and
never touch a collective themselves.

Batch math is deliberately REPLICATED (ids and gradients identical on
every device): the subsystem scales table *memory* with the mesh, not
batch compute — sharding the batch would make per-shard partial sums
mesh-width-dependent and break the cross-mesh bitwise contract.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.embeddings import sparse
from deeplearning4j_tpu.parallel.compat import shard_map_compat
from deeplearning4j_tpu.parallel.mesh import build_mesh

# -- metrics (lazy module-level instruments, nn/core.py idiom) ----------

_SHARD_BYTES = None
_ROWS_TOUCHED = None
_LOOKUP_MS = None
_SCATTER_MS = None


def _instruments():
    global _SHARD_BYTES, _ROWS_TOUCHED, _LOOKUP_MS, _SCATTER_MS
    if _SHARD_BYTES is None:
        from deeplearning4j_tpu.observability.metrics import (
            default_registry,
        )

        reg = default_registry()
        _SHARD_BYTES = reg.gauge(
            "embedding_shard_bytes",
            help="embedding-table bytes resident on ONE device (the "
                 "row shard; ~1/N of the replicated table on an "
                 "N-wide data axis)",
        )._default()
        _ROWS_TOUCHED = reg.gauge(
            "embedding_rows_touched",
            help="unique embedding rows written by the last sparse "
                 "update (the quantity per-step cost scales with, "
                 "instead of vocab)",
        )._default()
        _LOOKUP_MS = reg.summary(
            "embedding_lookup_ms",
            help="sharded embedding lookup wall time (ms): owned-row "
                 "gather + psum exchange, measured to completion",
        )._default()
        _SCATTER_MS = reg.summary(
            "embedding_scatter_ms",
            help="sparse embedding update wall time (ms): dedup + "
                 "segment_sum + owner-side scatter-add, measured to "
                 "completion",
        )._default()
    return _SHARD_BYTES, _ROWS_TOUCHED, _LOOKUP_MS, _SCATTER_MS


def note_shard_bytes(nbytes: int) -> None:
    _instruments()[0].set(float(nbytes))


def note_rows_touched(n: int) -> None:
    _instruments()[1].set(float(n))


def note_lookup_ms(ms: float) -> None:
    _instruments()[2].observe(float(ms))


def note_scatter_ms(ms: float) -> None:
    _instruments()[3].observe(float(ms))


# -- per-shard primitives (called inside shard_map over "data") ---------


def owned_rows(local_table, ids):
    """Gather ``ids`` against this shard's rows: out-of-shard ids read
    a clamped row but are masked to exact ``0.0`` before the ``psum``,
    so the sum over shards reconstructs ``table[ids]`` bitwise (every
    non-owner term is ``+0.0``). ``ids`` may be any integer shape; the
    result appends the row dim."""
    shard = local_table.shape[0]
    base = jax.lax.axis_index("data") * shard
    local = ids.astype(jnp.int32) - base
    own = (local >= 0) & (local < shard)
    rows = jnp.take(local_table, jnp.clip(local, 0, shard - 1), axis=0)
    rows = jnp.where(own[..., None], rows, jnp.zeros((), rows.dtype))
    return jax.lax.psum(rows, "data")


def scatter_owned(local_table, uids, deltas):
    """Add ``deltas[j]`` to row ``uids[j]`` on its owner shard only.
    ``uids`` comes from ``sparse.dedup_segment_sum`` (unique, PAD_ID
    padding), so every row is rewritten at most once — no cross-shard
    accumulation, no collective, and the per-row arithmetic is
    identical on every mesh width."""
    shard = local_table.shape[0]
    base = jax.lax.axis_index("data") * shard
    local = uids.astype(jnp.int32) - base
    own = (local >= 0) & (local < shard)
    idx = jnp.clip(local, 0, shard - 1)
    upd = jnp.where(
        own[:, None], deltas, jnp.zeros((), deltas.dtype)
    ).astype(local_table.dtype)
    return local_table.at[idx].add(upd)


# -- jitted mesh programs (cached per mesh) -----------------------------

_ROW = P("data", None)
_REP = P()


@functools.lru_cache(maxsize=None)
def _build_lookup(mesh):
    sm = shard_map_compat()
    body = sm(owned_rows, mesh=mesh, in_specs=(_ROW, _REP),
              out_specs=_REP)
    return jax.jit(body)


@functools.lru_cache(maxsize=None)
def _build_sparse_apply(mesh):
    """jit(table, ids, grads, alpha) -> (new_table, rows_touched):
    dedup outside the shard_map (replicated math), owner scatter
    inside it. The table buffer is donated — the update is in-place
    per shard."""
    sm = shard_map_compat()
    scatter = sm(scatter_owned, mesh=mesh,
                 in_specs=(_ROW, _REP, _REP), out_specs=_ROW)

    def apply(table, ids, grads, alpha):
        uids, summed, n = sparse.dedup_segment_sum(ids, grads)
        return scatter(table, uids, -alpha * summed), n

    return jax.jit(apply, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _build_sg_ns_step(mesh):
    """Fused skip-gram negative-sampling step over sharded syn0 /
    syn1neg: collective lookup -> replicated loss/grad over the
    GATHERED rows only (same objective as ``nlp/word2vec.py``'s
    ``_ns_step_raw``, collision mask included) -> dedup -> owner
    scatter. One dispatch; no ``[V, D]`` intermediate beyond the
    sharded tables themselves."""
    sm = shard_map_compat()

    def body(s0, s1n, centers, contexts, negs, mask, alpha):
        v = owned_rows(s0, centers)          # [B, D]
        u_pos = owned_rows(s1n, contexts)    # [B, D]
        u_neg = owned_rows(s1n, negs)        # [B, K, D]

        def loss_fn(v_, up_, un_):
            pos = jax.nn.log_sigmoid(jnp.sum(v_ * up_, axis=-1))
            nvalid = (negs != contexts[:, None]).astype(v_.dtype)
            neg = jnp.sum(
                nvalid * jax.nn.log_sigmoid(
                    -jnp.einsum("bd,bkd->bk", v_, un_)
                ),
                axis=-1,
            )
            return -jnp.sum(mask * (pos + neg)) / jnp.maximum(
                jnp.sum(mask), 1.0
            )

        loss, (gv, gp, gn) = sparse.rows_grad(loss_fn, v, u_pos, u_neg)
        u0, g0, n0 = sparse.dedup_segment_sum(centers, gv)
        ids1, rows1 = sparse.flatten_occurrences(
            jnp.concatenate([contexts, negs.reshape(-1)]),
            jnp.concatenate([gp, gn.reshape(-1, gn.shape[-1])]),
        )
        u1, g1, n1 = sparse.dedup_segment_sum(ids1, rows1)
        s0 = scatter_owned(s0, u0, -alpha * g0)
        s1n = scatter_owned(s1n, u1, -alpha * g1)
        return s0, s1n, loss, n0 + n1

    step = sm(body, mesh=mesh,
              in_specs=(_ROW, _ROW, _REP, _REP, _REP, _REP, _REP),
              out_specs=(_ROW, _ROW, _REP, _REP))
    return jax.jit(step, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=None)
def _build_hs_graph_step(mesh):
    """Fused hierarchical-softmax step over sharded vertex vectors /
    inner-node weights, graph sign convention (``graph/deepwalk.py``
    ``_hs_graph_step``: loss per node -log sigmoid((2·bit-1)·dot))."""
    sm = shard_map_compat()

    def body(s0, s1, centers, codes, points, pmask, alpha):
        v = owned_rows(s0, centers)          # [B, D]
        u = owned_rows(s1, points)           # [B, L, D]

        def loss_fn(v_, u_):
            x = jnp.einsum("bd,bld->bl", v_, u_)
            sign = 2.0 * codes - 1.0
            logp = jax.nn.log_sigmoid(sign * x)
            return -jnp.sum(pmask * logp) / jnp.maximum(
                jnp.sum(jnp.any(pmask > 0, axis=1)), 1.0
            )

        loss, (gv, gu) = sparse.rows_grad(loss_fn, v, u)
        u0, g0, n0 = sparse.dedup_segment_sum(centers, gv)
        ids1, rows1 = sparse.flatten_occurrences(points, gu)
        u1, g1, n1 = sparse.dedup_segment_sum(ids1, rows1)
        s0 = scatter_owned(s0, u0, -alpha * g0)
        s1 = scatter_owned(s1, u1, -alpha * g1)
        return s0, s1, loss, n0 + n1

    step = sm(body, mesh=mesh,
              in_specs=(_ROW, _ROW, _REP, _REP, _REP, _REP, _REP),
              out_specs=(_ROW, _ROW, _REP, _REP))
    return jax.jit(step, donate_argnums=(0, 1))


# -- the table ----------------------------------------------------------


class ShardedEmbeddingTable:
    """A ``[V, D]`` embedding table row-sharded ``P("data", None)``.

    ``V`` is zero-padded up to a multiple of the data-axis width (the
    pad rows are never owned by any valid id, so they are inert);
    queries and checkpoints always see the canonical unpadded rows.

    The device arrays live on ``self.table``; the fused workload steps
    (``_build_sg_ns_step`` / ``_build_hs_graph_step``) operate on the
    raw arrays of two tables at once, so Word2Vec/DeepWalk thread
    ``table.table`` through their jitted programs directly.
    """

    def __init__(self, vocab: int, dim: int, *, mesh=None,
                 dtype=jnp.float32, seed: int = 12345, rows=None):
        self.mesh = mesh if mesh is not None else build_mesh()
        self.n_shards = int(self.mesh.shape["data"])
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.padded_vocab = -(-self.vocab // self.n_shards) * self.n_shards
        self.dtype = jnp.dtype(dtype)
        if rows is None:
            # word2vec resetWeights convention: U(-0.5, 0.5)/dim
            rng = np.random.RandomState(seed)
            rows = (
                (rng.rand(self.vocab, self.dim) - 0.5) / self.dim
            ).astype(self.dtype)
        self.table = self._place(rows)

    @classmethod
    def zeros(cls, vocab: int, dim: int, *, mesh=None,
              dtype=jnp.float32) -> "ShardedEmbeddingTable":
        return cls(vocab, dim, mesh=mesh, dtype=dtype,
                   rows=np.zeros((vocab, dim), dtype))

    @classmethod
    def from_rows(cls, rows, *, mesh=None) -> "ShardedEmbeddingTable":
        rows = np.asarray(rows)
        return cls(rows.shape[0], rows.shape[1], mesh=mesh,
                   dtype=rows.dtype, rows=rows)

    # -- placement / persistence ---------------------------------------

    def _place(self, rows):
        rows = np.asarray(rows)
        if rows.shape != (self.vocab, self.dim):
            raise ValueError(
                f"rows shape {rows.shape} != ({self.vocab}, {self.dim})"
            )
        host = np.zeros((self.padded_vocab, self.dim), self.dtype)
        host[: self.vocab] = rows
        placed = jax.device_put(
            host, NamedSharding(self.mesh, _ROW)
        )
        note_shard_bytes(self.shard_bytes(placed))
        return placed

    def shard_bytes(self, table=None) -> int:
        """Bytes of ONE device's row shard (what
        ``embedding_shard_bytes`` publishes; ~1/N of
        ``replicated_bytes``)."""
        t = self.table if table is None else table
        shards = t.addressable_shards
        return int(shards[0].data.nbytes) if shards else 0

    def replicated_bytes(self) -> int:
        """Bytes a replicated copy of the (padded) table would pin on
        EVERY device — the baseline the shard ratio is measured
        against."""
        return self.padded_vocab * self.dim * self.dtype.itemsize

    def to_host(self) -> np.ndarray:
        """Canonical unpadded host rows — the mesh-independent form
        checkpoints persist (gather-then-save; restore re-shards onto
        whatever mesh is present)."""
        return np.asarray(self.table)[: self.vocab].copy()

    def restore_rows(self, rows) -> None:
        """Re-place canonical host rows onto THIS table's mesh (the
        resume half of the canonicalize-gather-then-reshard
        discipline; the source mesh's width is irrelevant)."""
        self.table = self._place(rows)

    # -- ops ------------------------------------------------------------

    def lookup(self, ids):
        """``table[ids]`` (canonical row values, any id shape), via the
        sharded owned-rows gather + psum exchange. Bitwise equal to an
        unsharded gather."""
        t0 = time.perf_counter()
        out = _build_lookup(self.mesh)(
            self.table, jnp.asarray(ids, jnp.int32)
        )
        out.block_until_ready()
        note_lookup_ms((time.perf_counter() - t0) * 1000.0)
        return out

    def apply_sparse_grads(self, ids, grads, lr) -> int:
        """SGD row update from per-occurrence gradients: dedup +
        ``segment_sum`` + owner scatter-add. Returns (and gauges) the
        unique rows touched; cost scales with that count, not with
        ``V``. ``ids``/``grads`` may carry extra leading dims."""
        ids = jnp.asarray(ids, jnp.int32)
        grads = jnp.asarray(grads, self.dtype)
        ids, grads = sparse.flatten_occurrences(ids, grads)
        t0 = time.perf_counter()
        self.table, n = _build_sparse_apply(self.mesh)(
            self.table, ids, grads, jnp.asarray(lr, self.dtype)
        )
        self.table.block_until_ready()
        note_scatter_ms((time.perf_counter() - t0) * 1000.0)
        touched = int(n)
        note_rows_touched(touched)
        return touched
