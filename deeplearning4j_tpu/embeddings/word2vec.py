"""``ShardedWord2Vec``: the negative-sampling skip-gram path rebuilt
on mesh-row-sharded tables.

The single-device ``nlp/word2vec.py`` trainer jits its own dense
``[V, D]`` syn0/syn1neg — fine until the vocabulary outgrows one
device. This subclass keeps every piece of its training recipe —
vocab, subsampling, pair generation, negative sampling, the lr
schedule, batch padding, the loss itself — and swaps ONLY the storage
and step: tables live as :class:`ShardedEmbeddingTable` shards
(``P("data", None)``) and each batch runs the fused
collective-lookup → rows-grad → dedup → owner-scatter step from
``embeddings/table.py``.

Differences from the base trainer, all deliberate:

- **Eligibility**: skip-gram + negative sampling only. CBOW and
  hierarchical softmax fall back to the base ``Word2Vec`` (the
  constructor refuses them loudly rather than silently training
  something else); the scan-fused and device-gen epoch paths are
  bypassed the same way (the sharded step IS the fused dispatch).
- **Resumable fit**: the epoch/offset/step/lr-schedule counters are
  first-class state, checkpointed with the canonical host rows, so a
  run killed mid-epoch resumes bitwise — on a mesh of ANY width,
  because lookup psums exact zeros and the deduped update math is
  mesh-independent (see table.py).
- **Data defense**: every batch passes an id-range gate before
  touching the tables; a corrupt batch (ids outside ``[0, V)``) is
  quarantined — counted via the shared
  ``batches_quarantined_total{reason="label_range"}`` counter — and
  skipped, exactly the posture of ``datasets/validate.py`` for the
  engine pipelines.

Persistence is canonical host rows (``save``/``restore`` below):
gather-then-save, restore re-shards onto whatever mesh is present —
train sharded on 8 devices, resume on 1, bitwise.
"""

from __future__ import annotations

import io
import os

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.embeddings.table import (
    ShardedEmbeddingTable,
    _build_sg_ns_step,
    note_rows_touched,
)
from deeplearning4j_tpu.nlp.word2vec import InMemoryLookupTable, Word2Vec
from deeplearning4j_tpu.parallel.mesh import build_mesh

_FORMAT = "sharded-word2vec-v1"


class ShardedLookupTable(InMemoryLookupTable):
    """Drop-in lookup table whose syn0/syn1neg are row-sharded over the
    mesh. The dense ``[V, D]`` device arrays of the base class never
    materialize — rows are drawn on host (same RNG stream as the base,
    so initial weights are bitwise identical) and placed shard-by-shard.
    """

    def __init__(self, cache, layer_size: int, seed: int = 12345,
                 use_hs: bool = False, negative: int = 5, mesh=None):
        # No super().__init__: it would allocate the dense tables this
        # class exists to avoid.
        self.cache = cache
        self.layer_size = layer_size
        self.use_hs = use_hs
        self.negative = negative
        self.mesh = mesh if mesh is not None else build_mesh()
        v = len(cache)
        rng = np.random.RandomState(seed)
        rows0 = (
            (rng.rand(v, layer_size) - 0.5) / layer_size
        ).astype(np.float32)
        self.t0 = ShardedEmbeddingTable.from_rows(rows0, mesh=self.mesh)
        self.t1 = (
            ShardedEmbeddingTable.zeros(v, layer_size, mesh=self.mesh)
            if use_hs else None
        )
        self.t1n = (
            ShardedEmbeddingTable.zeros(v, layer_size, mesh=self.mesh)
            if negative > 0 else None
        )
        self._normalized = None

    # The raw sharded device arrays, under the base-class names (query
    # helpers index them; padded tail rows sit past every valid index).
    @property
    def syn0(self):
        return self.t0.table

    @property
    def syn1(self):
        return None if self.t1 is None else self.t1.table

    @property
    def syn1neg(self):
        return None if self.t1n is None else self.t1n.table

    def normalized(self) -> np.ndarray:
        # Base reads np.asarray(self.syn0) — that would include the
        # vocab-padding rows; gather the canonical unpadded rows.
        if self._normalized is None:
            m = self.t0.to_host()
            norms = np.linalg.norm(m, axis=1, keepdims=True)
            self._normalized = m / np.maximum(norms, 1e-12)
        return self._normalized


class ShardedWord2Vec(Word2Vec):
    """Word2Vec whose tables shard over the mesh's data axis.

    Same constructor surface as :class:`Word2Vec` plus:

    - ``mesh``: the device mesh to shard over (default
      ``parallel.mesh.build_mesh()``).
    - ``checkpoint_path`` / ``checkpoint_every``: save canonical rows +
      fit counters every N steps during ``fit()`` (0 = only on demand).
    """

    def __init__(self, cache, sentences_ids, *, mesh=None,
                 checkpoint_path=None, checkpoint_every: int = 0, **kw):
        if kw.get("use_hierarchic_softmax"):
            raise ValueError(
                "ShardedWord2Vec supports negative sampling only; "
                "hierarchical softmax falls back to the single-device "
                "Word2Vec"
            )
        if kw.get("algorithm", "SkipGram") != "SkipGram":
            raise ValueError(
                "ShardedWord2Vec supports SkipGram only; CBOW falls "
                "back to the single-device Word2Vec"
            )
        self.mesh = mesh if mesh is not None else build_mesh()
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = int(checkpoint_every)
        # resumable-fit counters (all persisted by save())
        self._fit_epoch = 0
        self._fit_offset = 0
        self._fit_step = 0
        self._total_items = None
        self._quarantined = 0
        super().__init__(cache, sentences_ids, **kw)

    def _make_lookup(self):
        return ShardedLookupTable(
            self.cache, self.layer_size, seed=self.seed,
            use_hs=self.use_hs, negative=self.negative, mesh=self.mesh,
        )

    # -- data defense ----------------------------------------------------

    def _defend_batch(self, centers, contexts, mask) -> bool:
        """Id-range gate: any id outside ``[0, V)`` in a live slot
        quarantines the whole batch (count + skip), mirroring the
        validator posture of ``datasets/validate.py``. Returns True if
        the batch may train."""
        v = len(self.cache)
        live = mask > 0
        ok = True
        for ids in (centers, contexts):
            bad = (ids < 0) | (ids >= v)
            if bool(np.any(bad & live)):
                ok = False
                break
        if not ok:
            from deeplearning4j_tpu.datasets.validate import (
                REASON_LABEL_RANGE,
                _quarantine_metrics,
            )

            _quarantine_metrics()[0].labels(REASON_LABEL_RANGE).inc()
            self._quarantined += 1
        return ok

    # -- training --------------------------------------------------------

    def _apply_batch(self, centers, contexts, mask, alpha, step):
        if not self._defend_batch(np.asarray(centers),
                                  np.asarray(contexts),
                                  np.asarray(mask)):
            return
        lk = self.lookup
        negs = self._sample_negatives(len(centers), step)
        step_fn = _build_sg_ns_step(self.mesh)
        (lk.t0.table, lk.t1n.table, self._last_loss,
         self._last_rows_touched) = step_fn(
            lk.t0.table, lk.t1n.table,
            jnp.asarray(np.asarray(centers, np.int32)),
            jnp.asarray(np.asarray(contexts, np.int32)),
            jnp.asarray(np.asarray(negs, np.int32)),
            jnp.asarray(mask),
            jnp.float32(alpha),
        )

    def fit(self) -> None:
        """Resumable mirror of the base per-batch fit loop: identical
        epoch seeds, padding, lr schedule, and negative-sampling step
        seeds — plus (epoch, offset, step) counters that persist
        through ``save``/``restore`` so a killed run continues exactly
        where it died. A completed fit resets the counters (repeated
        ``fit()`` calls replay from scratch, like the base class)."""
        B = self.batch_size
        lr0, lr_min = self.learning_rate, self.min_learning_rate
        total_items = self._total_items
        step = self._fit_step
        if self._fit_epoch > 0 and total_items is None:
            raise ValueError(
                "resume state names epoch "
                f"{self._fit_epoch} but carries no total_items — "
                "checkpoint predates the first epoch's pair count"
            )
        for epoch in range(self._fit_epoch, self.epochs):
            ep_seed = self.seed + 31 * epoch
            c, o = self._gen_pairs(ep_seed)
            n_items = len(c)
            if total_items is None:
                total_items = max(n_items * self.epochs, 1)
                self._total_items = total_items
            start = self._fit_offset if epoch == self._fit_epoch else 0
            for s in range(start, n_items, B):
                mask = np.ones(B, np.float32)
                cb, ob = c[s:s + B], o[s:s + B]
                if len(cb) < B:
                    pad = B - len(cb)
                    mask[len(cb):] = 0.0
                    cb = np.pad(cb, (0, pad))
                    ob = np.pad(ob, (0, pad))
                frac = min((step * B) / total_items, 1.0)
                alpha = max(lr0 * (1 - frac), lr_min)
                for _ in range(self.iterations):
                    self._apply_batch(cb, ob, mask, alpha, step)
                step += 1
                self._fit_step = step
                self._fit_offset = s + B
                if (self.checkpoint_every > 0 and self.checkpoint_path
                        and step % self.checkpoint_every == 0):
                    self.save(self.checkpoint_path)
            self._fit_epoch = epoch + 1
            self._fit_offset = 0
        if getattr(self, "_last_rows_touched", None) is not None:
            note_rows_touched(int(self._last_rows_touched))
        # fit complete: back to a fresh schedule, like the base class
        self._fit_epoch = 0
        self._fit_offset = 0
        self._fit_step = 0
        self._total_items = None
        if self.checkpoint_every > 0 and self.checkpoint_path:
            self.save(self.checkpoint_path)
        self.lookup.invalidate_norms()

    # -- persistence -----------------------------------------------------

    def save(self, path: str) -> None:
        """Canonical host rows + fit counters, written atomically. The
        rows are unpadded and mesh-independent: a checkpoint written
        from an 8-wide mesh restores onto 1 device (or vice versa)
        bitwise."""
        from deeplearning4j_tpu.resilience.checkpoint import (
            atomic_write_bytes,
        )

        lk = self.lookup
        buf = io.BytesIO()
        np.savez(
            buf,
            format=_FORMAT,
            syn0=lk.t0.to_host(),
            syn1neg=lk.t1n.to_host(),
            fit_epoch=self._fit_epoch,
            fit_offset=self._fit_offset,
            fit_step=self._fit_step,
            total_items=(-1 if self._total_items is None
                         else self._total_items),
            meta=np.array([len(self.cache), self.layer_size,
                           self.negative, self.batch_size, self.epochs,
                           self.seed, self.window], np.int64),
        )
        atomic_write_bytes(os.fspath(path), buf.getvalue())

    def restore(self, path: str) -> None:
        """Load a checkpoint's rows onto THIS instance's mesh and adopt
        its fit counters. The source mesh's width is irrelevant."""
        with np.load(path, allow_pickle=False) as z:
            if str(z["format"]) != _FORMAT:
                raise ValueError(
                    f"not a {_FORMAT} checkpoint: {path}"
                )
            meta = z["meta"]
            want = np.array([len(self.cache), self.layer_size,
                             self.negative, self.batch_size, self.epochs,
                             self.seed, self.window], np.int64)
            if not np.array_equal(meta, want):
                raise ValueError(
                    "checkpoint hyperparameters "
                    f"{meta.tolist()} do not match this trainer's "
                    f"{want.tolist()} (vocab/layer/negative/batch/"
                    "epochs/seed/window)"
                )
            lk = self.lookup
            lk.t0.restore_rows(z["syn0"])
            lk.t1n.restore_rows(z["syn1neg"])
            self._fit_epoch = int(z["fit_epoch"])
            self._fit_offset = int(z["fit_offset"])
            self._fit_step = int(z["fit_step"])
            ti = int(z["total_items"])
            self._total_items = None if ti < 0 else ti
        self.lookup.invalidate_norms()
