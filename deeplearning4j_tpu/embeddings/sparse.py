"""Sparse embedding-gradient machinery: unique-id dedup +
``segment_sum`` scatter-add.

The dense way to update an embedding table is ``jax.grad`` through the
row gather: its VJP materializes a full ``[V, D]`` cotangent (zeros
plus a scatter) every step, and any stateful optimizer then carries
``[V, D]`` moments — both scale with the vocabulary, not with the rows
a batch actually touches. TensorFlow's large-scale design (PAPERS.md,
arxiv 1605.08695) treats sparse lookup/update as a first-class
primitive for exactly this reason.

Here the gradient is taken with respect to the GATHERED rows only
(``[B, D]`` — batch-sized), duplicate ids inside the batch are folded
with a sort + ``segment_sum`` (one summed gradient row per unique id,
matching the dense scatter-add semantics), and the update applies
those summed rows back with one scatter-add. Per-step cost scales with
rows touched, never with ``V``; ``tests/test_embeddings.py`` asserts
the jaxpr of the sparse step contains no ``[V, D]`` intermediate
beyond the table itself.

Everything in this module is pure jit-safe array math with NO
collectives — the mesh-aware exchange lives in ``embeddings/table.py``
(the one collective site ``scripts/lint_parity.py`` admits for this
package).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: Sentinel id marking padded slots in a deduped id vector. Negative,
#: so the masked scatter in table.py (and ``apply_rows_dense`` below)
#: can never own it.
PAD_ID = -1


def dedup_segment_sum(ids, grads):
    """Fold duplicate ids: ``(unique_ids, summed_grads, n_unique)``.

    ``ids``: int ``[B]``; ``grads``: ``[B, D]`` per-occurrence gradient
    rows. Returns fixed shapes (``[B]`` / ``[B, D]`` — jit-static):
    slot ``j < n_unique`` holds the j-th unique id (ascending) and the
    sum of its occurrences' gradient rows; slots ``>= n_unique`` hold
    ``PAD_ID`` and zeros. Duplicates are summed in sorted-position
    order, so the result is a pure function of (ids, grads) —
    independent of mesh shape, which is what makes the sharded update
    bitwise-reproducible across mesh widths.
    """
    b = ids.shape[0]
    ids = ids.astype(jnp.int32)
    order = jnp.argsort(ids)
    sid = jnp.take(ids, order, axis=0)
    sg = jnp.take(grads, order, axis=0)
    # first-occurrence flags -> segment index per sorted position
    first = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sid[1:] != sid[:-1]]
    )
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    summed = jax.ops.segment_sum(sg, seg, num_segments=b)
    n_unique = jnp.sum(first.astype(jnp.int32))
    # unique id per segment: scatter sorted ids at their segment slot
    # (drop-mode scatter; every slot < n_unique is written at least
    # once, with the same value each time)
    uids = jnp.full((b,), PAD_ID, jnp.int32).at[seg].set(sid)
    return uids, summed, n_unique


def rows_grad(loss_of_rows, *rows):
    """``(loss, grads)`` of a loss expressed over GATHERED rows.

    ``loss_of_rows(*rows)`` must be a scalar function of batch-sized
    row arrays (``[B, D]``, ``[B, K, D]``, ...). Differentiating here
    — instead of through the table gather — is what keeps the ``[V,
    D]`` cotangent out of the program entirely.
    """
    return jax.value_and_grad(
        lambda rs: loss_of_rows(*rs), argnums=0
    )(rows)


def flatten_occurrences(ids, grads):
    """Collapse leading batch dims: ``[..., D]`` gradient rows and
    matching ``[...]`` ids into flat ``[N]`` / ``[N, D]`` occurrence
    lists ready for :func:`dedup_segment_sum`."""
    d = grads.shape[-1]
    return ids.reshape(-1), grads.reshape(-1, d)


def apply_rows_dense(table, uids, summed, alpha):
    """Reference (unsharded) sparse SGD apply: one scatter-add of the
    deduped rows, ``table[uid] -= alpha * summed[uid]``. ``PAD_ID``
    slots contribute exact zeros at a clamped index, so padded slots
    never perturb row 0. This is the single-device twin of the
    per-shard owner update in ``table.py`` — the bitwise parity tests
    compare the two."""
    ok = (uids >= 0) & (uids < table.shape[0])
    idx = jnp.clip(uids, 0, table.shape[0] - 1)
    upd = jnp.where(ok[:, None], -alpha * summed, 0.0).astype(table.dtype)
    return table.at[idx].add(upd)
