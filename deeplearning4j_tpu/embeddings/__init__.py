"""Sharded embeddings: tables larger than one device's memory.

The L6 workloads (Word2Vec, DeepWalk) and the engines' EmbeddingLayer
all store a ``[V, D]`` table; everywhere else in this codebase that
table is dense on every device. This package makes the table's ROWS a
mesh resource — sharded ``P("data", None)``, a genuinely different
sharding shape from ZeRO's flat elementwise partitioning — so vocab
capacity scales with mesh width:

- ``sparse.py`` — the gradient discipline: differentiate w.r.t. the
  GATHERED rows (batch-sized, never ``[V, D]``), fold duplicate ids
  with sort + ``segment_sum``. Pure array math, no collectives.
- ``table.py`` — ``ShardedEmbeddingTable`` + the fused jitted steps:
  collective lookup (owned-rows gather + psum of exact zeros —
  bitwise equal to unsharded on any mesh width) and owner-only
  scatter-add updates. The package's single collective site
  (``scripts/lint_parity.py`` enforces this).
- ``word2vec.py`` / ``deepwalk.py`` — ``ShardedWord2Vec`` and
  ``ShardedDeepWalk``: the single-device trainers' exact recipes on
  sharded storage, with resumable fits and canonical-host-row
  checkpoints that restore onto a mesh of any width, bitwise.

The engine-side twin is ``nn/layers/feedforward.py``'s
``SparseEmbeddingLayer`` (sparse row updates through ``nn/core.py`` +
``DistributedTrainer``, with explicit megastep/ZeRO eligibility
fallbacks). Metrics: ``embedding_shard_bytes``,
``embedding_rows_touched``, ``embedding_lookup_ms``,
``embedding_scatter_ms`` (docs/ARCHITECTURE.md catalog).
"""

from deeplearning4j_tpu.embeddings.sparse import (  # noqa: F401
    PAD_ID,
    apply_rows_dense,
    dedup_segment_sum,
    flatten_occurrences,
    rows_grad,
)
from deeplearning4j_tpu.embeddings.table import (  # noqa: F401
    ShardedEmbeddingTable,
    note_lookup_ms,
    note_rows_touched,
    note_scatter_ms,
    note_shard_bytes,
)
from deeplearning4j_tpu.embeddings.word2vec import (  # noqa: F401
    ShardedLookupTable,
    ShardedWord2Vec,
)
from deeplearning4j_tpu.embeddings.deepwalk import (  # noqa: F401
    ShardedDeepWalk,
    ShardedGraphLookupTable,
)

__all__ = [
    "PAD_ID",
    "ShardedDeepWalk",
    "ShardedEmbeddingTable",
    "ShardedGraphLookupTable",
    "ShardedLookupTable",
    "ShardedWord2Vec",
    "apply_rows_dense",
    "dedup_segment_sum",
    "flatten_occurrences",
    "note_lookup_ms",
    "note_rows_touched",
    "note_scatter_ms",
    "note_shard_bytes",
    "rows_grad",
]
