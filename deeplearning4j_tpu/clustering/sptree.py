"""Space-partitioning tree (reference
``clustering/sptree/SpTree.java`` + ``Cell.java``): the Barnes-Hut
approximation structure behind ``BarnesHutTsne`` — each node stores a
center of mass; distant cells act as one superpoint when
width/distance < theta."""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class Cell:
    """Axis-aligned cell: center + half-width per dim (reference
    ``clustering/sptree/Cell.java``)."""

    def __init__(self, center: np.ndarray, width: np.ndarray):
        self.center = np.asarray(center, np.float64)
        self.width = np.asarray(width, np.float64)

    def contains(self, point: np.ndarray) -> bool:
        return bool(np.all(np.abs(point - self.center) <= self.width))


class SPTree:
    """Reference ``SpTree.java``: build over data [N, D], then
    ``compute_non_edge_forces`` per point (repulsive term) and the
    static ``compute_edge_forces`` over the sparse P (attractive
    term)."""

    NODE_CAPACITY = 1

    def __init__(self, data: np.ndarray,
                 cell: Optional[Cell] = None,
                 indices: Optional[np.ndarray] = None):
        self.data = np.asarray(data, np.float64)
        n, d = self.data.shape
        self.dims = d
        if cell is None:
            mins = self.data.min(axis=0)
            maxs = self.data.max(axis=0)
            center = (mins + maxs) / 2.0
            width = (maxs - mins) / 2.0 + 1e-5
            cell = Cell(center, width)
        self.cell = cell
        self.children: List[Optional[SPTree]] = [None] * (2 ** d)
        self.is_leaf = True
        self.cum_size = 0
        self.center_of_mass = np.zeros(d)
        self.point_index = -1  # index stored at this leaf
        if indices is None:
            indices = np.arange(n)
        for i in indices:
            self.insert(int(i))

    # -- construction ---------------------------------------------------

    def _child_slot(self, point: np.ndarray) -> int:
        slot = 0
        for dim in range(self.dims):
            if point[dim] > self.cell.center[dim]:
                slot |= 1 << dim
        return slot

    def _child_cell(self, slot: int) -> Cell:
        half = self.cell.width / 2.0
        center = self.cell.center.copy()
        for dim in range(self.dims):
            center[dim] += half[dim] if (slot >> dim) & 1 else -half[dim]
        return Cell(center, half)

    def insert(self, index: int) -> bool:
        point = self.data[index]
        if not self.cell.contains(point):
            return False
        self.cum_size += 1
        # online center-of-mass update
        self.center_of_mass += (point - self.center_of_mass) / self.cum_size
        if self.is_leaf and self.point_index < 0:
            self.point_index = index
            return True
        # duplicate point: keep weight in cum_size, don't subdivide
        if self.is_leaf and np.array_equal(
            self.data[self.point_index], point
        ):
            return True
        if self.is_leaf:
            self._subdivide()
        return self._insert_child(index)

    def _subdivide(self) -> None:
        old = self.point_index
        self.is_leaf = False
        self.point_index = -1
        self._insert_child(old)

    def _insert_child(self, index: int) -> bool:
        slot = self._child_slot(self.data[index])
        if self.children[slot] is None:
            child = SPTree.__new__(SPTree)
            child.data = self.data
            child.dims = self.dims
            child.cell = self._child_cell(slot)
            child.children = [None] * (2 ** self.dims)
            child.is_leaf = True
            child.cum_size = 0
            child.center_of_mass = np.zeros(self.dims)
            child.point_index = -1
            self.children[slot] = child
        return self.children[slot].insert(index)

    # -- forces ---------------------------------------------------------

    def compute_non_edge_forces(self, index: int, theta: float,
                                neg_f: np.ndarray) -> float:
        """Accumulate the repulsive force on point ``index`` into
        ``neg_f``; returns this subtree's contribution to sum_Q
        (reference ``SpTree.computeNonEdgeForces``)."""
        if self.cum_size == 0:
            return 0.0
        point = self.data[index]
        if self.is_leaf and self.point_index == index \
                and self.cum_size == 1:
            return 0.0
        diff = point - self.center_of_mass
        dist2 = float(diff @ diff)
        max_width = float(np.max(self.cell.width * 2.0))
        if self.is_leaf or (
            dist2 > 0 and max_width / np.sqrt(dist2) < theta
        ):
            # treat cell as a single superpoint of weight cum_size
            weight = self.cum_size
            if self.is_leaf and self.point_index == index:
                weight -= 1  # exclude self from own leaf
                if weight == 0:
                    return 0.0
            q = 1.0 / (1.0 + dist2)
            qz = weight * q
            neg_f += qz * q * diff
            return qz
        total = 0.0
        for child in self.children:
            if child is not None:
                total += child.compute_non_edge_forces(index, theta, neg_f)
        return total

    @staticmethod
    def compute_edge_forces(data: np.ndarray, rows: np.ndarray,
                            cols: np.ndarray, vals: np.ndarray,
                            pos_f: np.ndarray) -> None:
        """Attractive term over sparse symmetric P in CSR (rows[n+1],
        cols, vals), vectorized (reference
        ``SpTree.computeEdgeForces``)."""
        n = data.shape[0]
        counts = rows[1:] - rows[:-1]
        src = np.repeat(np.arange(n), counts)
        diff = data[src] - data[cols]                   # [nnz, D]
        q = 1.0 / (1.0 + np.sum(diff * diff, axis=1))   # [nnz]
        w = (vals * q)[:, None] * diff
        np.add.at(pos_f, src, w)

