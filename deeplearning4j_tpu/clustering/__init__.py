"""Clustering + spatial indexes (reference
``deeplearning4j-core/.../clustering`` — SURVEY.md §2.2): KMeans on
jitted Lloyd steps, KD-tree, VP-tree, quad/SP trees for Barnes-Hut."""

from deeplearning4j_tpu.clustering.cluster import (
    Cluster,
    ClusterSet,
    Point,
    PointClassification,
)
from deeplearning4j_tpu.clustering.kdtree import HyperRect, KDTree
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.quadtree import Cell as QuadCell, QuadTree
from deeplearning4j_tpu.clustering.sptree import Cell, SPTree
from deeplearning4j_tpu.clustering.vptree import DataPoint, VPTree

__all__ = [
    "Cluster", "ClusterSet", "Point", "PointClassification",
    "HyperRect", "KDTree", "KMeansClustering", "Cell", "QuadTree",
    "SPTree", "DataPoint", "VPTree", "QuadCell",
]
