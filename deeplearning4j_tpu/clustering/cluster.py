"""Cluster model objects (reference
``clustering/cluster/Point.java``, ``Cluster.java``,
``ClusterSet.java``, ``PointClassification.java``)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np


@dataclass
class Point:
    """A point with id + array (reference ``Point.java``)."""

    id: str
    array: np.ndarray
    label: Optional[str] = None

    @staticmethod
    def to_points(matrix: np.ndarray) -> List["Point"]:
        return [Point(str(i), np.asarray(row)) for i, row in
                enumerate(matrix)]


@dataclass
class Cluster:
    """A center plus its member points (reference ``Cluster.java``)."""

    center: Point
    points: List[Point] = field(default_factory=list)
    id: str = ""

    def add_point(self, p: Point) -> None:
        self.points.append(p)

    def get_distance_to_center(self, p: Point) -> float:
        return float(np.linalg.norm(p.array - self.center.array))


@dataclass
class PointClassification:
    """Result of classifying one point into a ClusterSet (reference
    ``PointClassification.java``)."""

    cluster: Cluster
    distance_from_center: float
    new_location: bool


class ClusterSet:
    """All clusters of one run (reference ``ClusterSet.java``)."""

    def __init__(self, clusters: Optional[List[Cluster]] = None):
        self.clusters: List[Cluster] = clusters or []

    def get_clusters(self) -> List[Cluster]:
        return self.clusters

    def get_cluster_count(self) -> int:
        return len(self.clusters)

    def centers(self) -> np.ndarray:
        return np.stack([c.center.array for c in self.clusters])

    def classify_point(self, p: Point,
                       move: bool = True) -> PointClassification:
        centers = self.centers()
        d = np.linalg.norm(centers - p.array[None, :], axis=1)
        best = int(np.argmin(d))
        cluster = self.clusters[best]
        was_member = any(q.id == p.id for q in cluster.points)
        if move and not was_member:
            for c in self.clusters:
                c.points = [q for q in c.points if q.id != p.id]
            cluster.add_point(p)
        return PointClassification(cluster, float(d[best]), not was_member)
