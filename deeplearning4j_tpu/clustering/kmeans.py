"""KMeans clustering (reference
``clustering/kmeans/KMeansClustering.java`` +
``clustering/algorithm/BaseClusteringAlgorithm.java`` and its
strategy/condition machinery).

TPU-first: the reference iterates points one at a time through
``ClusterUtils`` thread pools; here one jitted Lloyd step does the
full [N, K] distance matrix on the MXU (assign = argmin row,
update = masked mean) and the host loop only checks the termination
condition (fixed iteration count or distribution-variation rate,
mirroring ``FixedIterationCountCondition`` /
``ConvergenceCondition``)."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.cluster import (
    Cluster,
    ClusterSet,
    Point,
)

_DISTANCES = ("euclidean", "manhattan", "cosinesimilarity")


@functools.partial(jax.jit, static_argnames=("k", "distance"))
def _lloyd_step(x, centers, k: int, distance: str):
    if distance == "euclidean":
        d = jnp.sum((x[:, None, :] - centers[None, :, :]) ** 2, axis=-1)
    elif distance == "manhattan":
        d = jnp.sum(jnp.abs(x[:, None, :] - centers[None, :, :]), axis=-1)
    else:  # cosine similarity → distance
        xn = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True),
                             1e-12)
        cn = centers / jnp.maximum(
            jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-12
        )
        d = 1.0 - xn @ cn.T
    assign = jnp.argmin(d, axis=1)
    onehot = jax.nn.one_hot(assign, k, dtype=x.dtype)     # [N, K]
    counts = jnp.sum(onehot, axis=0)                      # [K]
    sums = onehot.T @ x                                   # [K, D]
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0),
        centers,
    )
    cost = jnp.sum(jnp.min(d, axis=1))
    return new_centers, assign, cost


class KMeansClustering:
    """Reference ``KMeansClustering.setup`` twins: fixed iteration
    count, or convergence on the distribution-variation rate."""

    def __init__(self, cluster_count: int,
                 max_iteration_count: Optional[int],
                 distance_function: str = "euclidean",
                 min_distribution_variation_rate: Optional[float] = None,
                 allow_empty_clusters: bool = True, seed: int = 12345):
        if distance_function not in _DISTANCES:
            raise ValueError(
                f"unknown distance {distance_function!r}; "
                f"expected one of {_DISTANCES}"
            )
        self.k = cluster_count
        self.max_iterations = max_iteration_count
        self.distance = distance_function
        self.min_variation = min_distribution_variation_rate
        self.allow_empty = allow_empty_clusters
        self.seed = seed
        self.iteration_count = 0

    @classmethod
    def setup(cls, cluster_count: int, max_iteration_count: int,
              distance_function: str = "euclidean",
              seed: int = 12345) -> "KMeansClustering":
        return cls(cluster_count, max_iteration_count, distance_function,
                   seed=seed)

    @classmethod
    def setup_convergence(
        cls, cluster_count: int,
        min_distribution_variation_rate: float,
        distance_function: str = "euclidean",
        allow_empty_clusters: bool = True, seed: int = 12345,
    ) -> "KMeansClustering":
        return cls(cluster_count, None, distance_function,
                   min_distribution_variation_rate, allow_empty_clusters,
                   seed)

    def _kmeans_pp_init(self, x: np.ndarray,
                        rng: np.random.RandomState) -> np.ndarray:
        """k-means++ seeding (D² sampling): far-apart initial centers,
        avoiding the bad local optima plain random choice falls into.
        (The reference seeds from random points —
        ``ClusterUtils.randomClusters``; ++ strictly improves on it.)"""
        n = x.shape[0]
        centers = np.empty((self.k, x.shape[1]), x.dtype)
        centers[0] = x[rng.randint(n)]
        d2 = np.sum((x - centers[0]) ** 2, axis=1)
        for i in range(1, self.k):
            total = float(d2.sum())
            if total <= 0.0:
                # remaining points are duplicates of chosen centers
                centers[i] = x[rng.randint(n)]
                continue
            centers[i] = x[rng.choice(n, p=d2 / total)]
            d2 = np.minimum(d2, np.sum((x - centers[i]) ** 2, axis=1))
        return centers

    def apply_to(self, points) -> ClusterSet:
        """Cluster the points (reference
        ``BaseClusteringAlgorithm.applyTo``)."""
        if isinstance(points, np.ndarray):
            pts = Point.to_points(points)
            x = np.asarray(points, np.float32)
        else:
            pts = list(points)
            x = np.stack([p.array for p in pts]).astype(np.float32)
        n = x.shape[0]
        if self.k > n:
            raise ValueError(f"k={self.k} > n_points={n}")
        rng = np.random.RandomState(self.seed)
        centers = jnp.asarray(self._kmeans_pp_init(x, rng))
        xj = jnp.asarray(x)
        prev_cost = None
        assign = None
        max_iters = self.max_iterations or 1000
        self.iteration_count = 0
        for _ in range(max_iters):
            centers, assign, cost = _lloyd_step(
                xj, centers, self.k, self.distance
            )
            self.iteration_count += 1
            cost = float(cost)
            if self.min_variation is not None and prev_cost is not None:
                denom = max(abs(prev_cost), 1e-12)
                if abs(prev_cost - cost) / denom < self.min_variation:
                    break
            prev_cost = cost
        assign = np.asarray(assign)
        centers = np.asarray(centers)
        clusters = [
            Cluster(Point(f"center-{i}", centers[i]), id=str(i))
            for i in range(self.k)
        ]
        for idx, p in zip(assign, pts):
            clusters[int(idx)].add_point(p)
        if not self.allow_empty:
            clusters = [c for c in clusters if c.points]
        return ClusterSet(clusters)
