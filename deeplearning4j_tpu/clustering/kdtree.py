"""KD-tree (reference ``clustering/kdtree/KDTree.java`` +
``HyperRect.java``): host-side spatial index for exact nearest
neighbors in low dimension."""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class HyperRect:
    """Axis-aligned bounding box (reference ``HyperRect.java``)."""

    def __init__(self, lower: np.ndarray, upper: np.ndarray):
        self.lower = np.asarray(lower, np.float64)
        self.upper = np.asarray(upper, np.float64)

    @staticmethod
    def infinite(dims: int) -> "HyperRect":
        return HyperRect(np.full(dims, -np.inf), np.full(dims, np.inf))

    def contains(self, point: np.ndarray) -> bool:
        return bool(
            np.all(point >= self.lower) and np.all(point <= self.upper)
        )

    def min_distance(self, point: np.ndarray) -> float:
        clipped = np.clip(point, self.lower, self.upper)
        return float(np.linalg.norm(point - clipped))

    def get_lower(self, point: np.ndarray, dim: int) -> "HyperRect":
        upper = self.upper.copy()
        upper[dim] = point[dim]
        return HyperRect(self.lower, upper)

    def get_upper(self, point: np.ndarray, dim: int) -> "HyperRect":
        lower = self.lower.copy()
        lower[dim] = point[dim]
        return HyperRect(lower, self.upper)


class _Node:
    __slots__ = ("point", "left", "right")

    def __init__(self, point: np.ndarray):
        self.point = point
        self.left: Optional[_Node] = None
        self.right: Optional[_Node] = None


class KDTree:
    """Insert-based KD-tree with nn / knn queries (reference
    ``KDTree.java`` — ``insert``, ``nn``, ``knn``)."""

    def __init__(self, dims: int):
        self.dims = dims
        self.root: Optional[_Node] = None
        self.size = 0

    def insert(self, point) -> None:
        point = np.asarray(point, np.float64)
        if point.shape[-1] != self.dims:
            raise ValueError(
                f"point dim {point.shape[-1]} != tree dim {self.dims}"
            )
        self.size += 1
        if self.root is None:
            self.root = _Node(point)
            return
        node, depth = self.root, 0
        while True:
            dim = depth % self.dims
            if point[dim] < node.point[dim]:
                if node.left is None:
                    node.left = _Node(point)
                    return
                node = node.left
            else:
                if node.right is None:
                    node.right = _Node(point)
                    return
                node = node.right
            depth += 1

    def nn(self, point) -> Tuple[float, np.ndarray]:
        """(distance, nearest point)."""
        res = self.knn(point, 1)
        return res[0]

    def knn(self, point, k: int) -> List[Tuple[float, np.ndarray]]:
        """k nearest as [(distance, point)] ascending."""
        point = np.asarray(point, np.float64)
        heap: List[Tuple[float, int, np.ndarray]] = []  # max-heap via neg
        counter = [0]

        def visit(node: Optional[_Node], depth: int):
            if node is None:
                return
            d = float(np.linalg.norm(point - node.point))
            if len(heap) < k:
                heapq.heappush(heap, (-d, counter[0], node.point))
                counter[0] += 1
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, counter[0], node.point))
                counter[0] += 1
            dim = depth % self.dims
            diff = point[dim] - node.point[dim]
            near, far = (
                (node.left, node.right) if diff < 0
                else (node.right, node.left)
            )
            visit(near, depth + 1)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far, depth + 1)

        visit(self.root, 0)
        return sorted([(-negd, p) for negd, _, p in heap],
                      key=lambda t: t[0])
