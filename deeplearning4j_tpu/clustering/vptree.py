"""Vantage-point tree (reference ``clustering/vptree/VPTree.java``):
metric-space k-NN index used by the UI nearest-neighbor view and
``TreeModelUtils.wordsNearest``. Distances to candidate sets are
computed as vectorized numpy batches rather than the reference's
per-pair ``CounterMap`` accounting."""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

EUCLIDEAN = "euclidean"
COSINE = "cosinesimilarity"


@dataclass
class DataPoint:
    """Indexed point (reference ``clustering/sptree/DataPoint.java``)."""

    index: int
    point: np.ndarray


class _VPNode:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index: int):
        self.index = index
        self.threshold = 0.0
        self.inside: Optional[_VPNode] = None
        self.outside: Optional[_VPNode] = None


class VPTree:
    """VP-tree over items [N, D] (reference ``VPTree.java``;
    similarity 'euclidean' or 'cosinesimilarity' with ``invert``
    flipping sign so larger-similarity = nearer)."""

    def __init__(self, items, similarity_function: str = EUCLIDEAN,
                 invert: bool = False, seed: int = 12345):
        if isinstance(items, list) and items and isinstance(
            items[0], DataPoint
        ):
            self.items = np.stack([p.point for p in items]).astype(
                np.float64
            )
        else:
            self.items = np.asarray(items, np.float64)
        if similarity_function not in (EUCLIDEAN, COSINE):
            raise ValueError(
                f"unknown similarity {similarity_function!r}; expected "
                f"{EUCLIDEAN!r} or {COSINE!r}"
            )
        self.similarity_function = similarity_function
        self.invert = invert
        self._rng = np.random.RandomState(seed)
        if self.similarity_function == COSINE:
            norms = np.linalg.norm(self.items, axis=1, keepdims=True)
            self._normed = self.items / np.maximum(norms, 1e-12)
        self.root = self._build(np.arange(len(self.items)))

    # -- distances ------------------------------------------------------

    def _dist(self, idx: int, candidates: np.ndarray) -> np.ndarray:
        """Distance from item idx to a batch of item indices."""
        return self._dist_vec(self.items[idx], candidates)

    def _dist_vec(self, q: np.ndarray, candidates: np.ndarray) -> np.ndarray:
        pts = self.items[candidates]
        if self.similarity_function == EUCLIDEAN:
            d = np.linalg.norm(pts - q[None, :], axis=1)
        else:
            # cosine is already converted to a dissimilarity here, so
            # `invert` must NOT flip it again (smaller = more similar)
            qn = q / max(float(np.linalg.norm(q)), 1e-12)
            d = 1.0 - self._normed[candidates] @ qn
        if self.invert and self.similarity_function == EUCLIDEAN:
            d = -d
        return d

    def _dist_point(self, q: np.ndarray, idx: int) -> float:
        return float(self._dist_vec(q, np.asarray([idx]))[0])

    # -- build ----------------------------------------------------------

    def _build(self, indices: np.ndarray) -> Optional[_VPNode]:
        if len(indices) == 0:
            return None
        vp_pos = self._rng.randint(len(indices))
        vp = int(indices[vp_pos])
        rest = np.delete(indices, vp_pos)
        node = _VPNode(vp)
        if len(rest) == 0:
            return node
        d = self._dist(vp, rest)
        node.threshold = float(np.median(d))
        inside = rest[d < node.threshold]
        outside = rest[d >= node.threshold]
        node.inside = self._build(inside)
        node.outside = self._build(outside)
        return node

    # -- search ---------------------------------------------------------

    def search(self, target, k: int) -> Tuple[List[int], List[float]]:
        """(indices, distances) of the k nearest items (reference
        ``VPTree.search(DataPoint, k, results, distances)``)."""
        q = np.asarray(
            target.point if isinstance(target, DataPoint) else target,
            np.float64,
        )
        if self.invert or self.similarity_function == COSINE:
            # negated distance and 1-cos both violate the triangle
            # inequality, so the tree's pruning bounds don't hold —
            # rank the whole set vectorized instead (one matmul)
            d = self._dist_vec(q, np.arange(len(self.items)))
            order = np.argsort(d, kind="stable")[:k]
            return order.tolist(), d[order].tolist()
        heap: List[Tuple[float, int]] = []  # max-heap via negation
        tau = [np.inf]

        def visit(node: Optional[_VPNode]):
            if node is None:
                return
            d = self._dist_point(q, node.index)
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, node.index))
                tau[0] = -heap[0][0]
            if node.inside is None and node.outside is None:
                return
            if d < node.threshold:
                visit(node.inside)
                if d + tau[0] >= node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self.root)
        pairs = sorted([(-negd, i) for negd, i in heap], key=lambda t: t[0])
        return [i for _, i in pairs], [d for d, _ in pairs]
