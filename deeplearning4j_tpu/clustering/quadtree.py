"""2-D quadtree (reference ``clustering/quadtree/QuadTree.java`` +
``Cell.java``): the dedicated two-dimensional Barnes-Hut structure
(the t-SNE paper's original formulation, arXiv:1301.3342) alongside
the d-dimensional ``SPTree``. Each node tracks a center of mass and a
cumulative size; distant quads act as one superpoint when
max(cell extent) / distance < theta.

Net-new vs the reference: ``knn`` best-first nearest-neighbour queries
over the same structure (the reference exposes KNN only through
KDTree/VPTree; a 2-D embedding viewer wants it here too).
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np

NODE_CAPACITY = 1  # reference QT_NODE_CAPACITY


class Cell:
    """Axis-aligned quad: center (x, y) + half-width / half-height
    (reference ``clustering/quadtree/Cell.java``)."""

    def __init__(self, x: float, y: float, hw: float, hh: float):
        self.x = float(x)
        self.y = float(y)
        self.hw = float(hw)
        self.hh = float(hh)

    def contains(self, point: np.ndarray) -> bool:
        # reference Cell.containsPoint: closed lower, open-ish upper
        # bounds via <=; symmetric about the center
        return bool(
            self.x - self.hw <= point[0] <= self.x + self.hw
            and self.y - self.hh <= point[1] <= self.y + self.hh
        )

    def min_sq_dist(self, point: np.ndarray) -> float:
        """Squared distance from ``point`` to the nearest point of the
        cell (0 inside) — the KNN pruning bound."""
        dx = max(abs(point[0] - self.x) - self.hw, 0.0)
        dy = max(abs(point[1] - self.y) - self.hh, 0.0)
        return dx * dx + dy * dy


class QuadTree:
    """Reference ``QuadTree.java``: build over data [N, 2], then
    ``compute_non_edge_forces`` (repulsive Barnes-Hut term) and
    ``compute_edge_forces`` (attractive term over sparse P)."""

    def __init__(self, data: np.ndarray,
                 cell: Optional[Cell] = None,
                 _fill: bool = True):
        data = np.asarray(data, np.float64)
        if data.ndim != 2 or data.shape[1] != 2:
            raise ValueError(
                f"QuadTree is 2-D only (reference QT_NO_DIMS=2); got "
                f"shape {data.shape}"
            )
        self.data = data
        if cell is None:
            mean = data.mean(axis=0)
            mins = data.min(axis=0)
            maxs = data.max(axis=0)
            # reference: half-extent = max one-sided spread + eps
            hw = max(maxs[0] - mean[0], mean[0] - mins[0]) + 1e-5
            hh = max(maxs[1] - mean[1], mean[1] - mins[1]) + 1e-5
            cell = Cell(mean[0], mean[1], hw, hh)
        self.boundary = cell
        self.nw: Optional[QuadTree] = None
        self.ne: Optional[QuadTree] = None
        self.sw: Optional[QuadTree] = None
        self.se: Optional[QuadTree] = None
        self.is_leaf = True
        self.size = 0
        self.cum_size = 0
        self.dup_weight = 0  # absorbed duplicates of the stored point
        self.center_of_mass = np.zeros(2)
        self.indices = np.full(NODE_CAPACITY, -1, np.int64)
        if _fill:
            for i in range(len(data)):
                self.insert(int(i))

    # -- construction ---------------------------------------------------

    def _child_for(self, point: np.ndarray) -> "QuadTree":
        """Pick the quadrant of ``point`` (reference ``findIndex``;
        the split is the cell CENTER — our cells store center +
        half-extent, so the reference's ``x + hw/2`` edge-convention
        arithmetic reduces to plain x/y here)."""
        left = point[0] <= self.boundary.x
        top = point[1] <= self.boundary.y
        if left:
            return self.nw if top else self.sw
        return self.ne if top else self.se

    def insert(self, new_index: int) -> bool:
        point = self.data[new_index]
        if not self.boundary.contains(point):
            return False
        # running center of mass (reference insert: incremental mean)
        self.cum_size += 1
        m1 = (self.cum_size - 1) / self.cum_size
        self.center_of_mass = (
            self.center_of_mass * m1 + point / self.cum_size
        )
        if self.is_leaf and self.size < NODE_CAPACITY:
            self.indices[self.size] = new_index
            self.size += 1
            return True
        # duplicate point: count it in cum_size/center but store once;
        # dup_weight rides along so subdivision doesn't strand the
        # absorbed mass at what becomes an internal node
        for i in range(self.size):
            if np.array_equal(self.data[self.indices[i]], point):
                self.dup_weight += 1
                return True
        if self.is_leaf:
            self._subdivide()
        if self._child_for(point).insert(new_index):
            return True
        # float boundary edge cases: try the remaining quads
        # (reference ``insertIntoOneOf``)
        return any(c.insert(new_index) for c in self._children())

    def _subdivide(self) -> None:
        b = self.boundary
        hw, hh = b.hw / 2, b.hh / 2
        mk = lambda cx, cy: QuadTree(
            self.data, Cell(cx, cy, hw, hh), _fill=False
        )
        self.nw = mk(b.x - hw, b.y - hh)
        self.ne = mk(b.x + hw, b.y - hh)
        self.sw = mk(b.x - hw, b.y + hh)
        self.se = mk(b.x + hw, b.y + hh)
        self.is_leaf = False
        # re-home the points stored at this node, carrying any
        # absorbed duplicate mass with the stored point (same
        # location, so the child's center of mass is unchanged)
        for i in range(self.size):
            idx = int(self.indices[i])
            child = self._child_for(self.data[idx])
            child.insert(idx)
            if self.dup_weight:
                child.cum_size += self.dup_weight
                child.dup_weight += self.dup_weight
        self.dup_weight = 0
        self.size = 0

    def _children(self) -> List["QuadTree"]:
        return [c for c in (self.nw, self.ne, self.sw, self.se)
                if c is not None]

    # -- validation / introspection -------------------------------------

    def is_correct(self) -> bool:
        for i in range(self.size):
            if not self.boundary.contains(self.data[self.indices[i]]):
                return False
        return self.is_leaf or all(
            c.is_correct() for c in self._children()
        )

    def depth(self) -> int:
        if self.is_leaf:
            return 1
        return 1 + max(c.depth() for c in self._children())

    # -- Barnes-Hut forces (t-SNE) ---------------------------------------

    def compute_non_edge_forces(self, point_index: int, theta: float,
                                negative_force: np.ndarray) -> float:
        """Accumulate the repulsive force on ``point_index`` into
        ``negative_force`` ([2] array, mutated); returns this node's
        contribution to sum_Q (reference passes an AtomicDouble)."""
        if self.cum_size == 0:
            return 0.0
        weight = self.cum_size
        if (self.is_leaf and self.size == 1
                and self.indices[0] == point_index):
            # own leaf: exclude self but keep absorbed duplicates —
            # they are distinct points that still repel (same
            # ``weight = cum_size - 1`` discipline as SPTree;
            # the reference's early return drops them from sum_Q)
            weight -= 1
            if weight == 0:
                return 0.0
        buf = self.data[point_index] - self.center_of_mass
        dist_sq = float(buf @ buf)
        if self.is_leaf or (
            max(self.boundary.hh, self.boundary.hw)
            / np.sqrt(max(dist_sq, 1e-300)) < theta
        ):
            q = 1.0 / (1.0 + dist_sq)
            mult = weight * q
            sum_q = mult
            negative_force += buf * (mult * q)
            return sum_q
        return sum(
            c.compute_non_edge_forces(point_index, theta, negative_force)
            for c in self._children()
        )

    def compute_edge_forces(self, row_p: np.ndarray, col_p: np.ndarray,
                            val_p: np.ndarray, n: int,
                            pos_f: np.ndarray) -> None:
        """Attractive forces over the CSR sparse P (reference
        ``computeEdgeForces``); ``pos_f`` [N, 2] is accumulated in
        place. Delegates to the vectorized SPTree implementation —
        same t-SNE attractive term val·(y_i-y_j)/(1+d²). (The
        reference's QuadTree divides by d² with no +1, which blows up
        on near-duplicate points; its own SpTree and van der Maaten's
        original both use 1+d² — deliberate fix, not an omission.)"""
        from deeplearning4j_tpu.clustering.sptree import SPTree

        row_p = np.asarray(row_p)
        if row_p.ndim != 1:
            raise ValueError("row_p must be a vector")
        SPTree.compute_edge_forces(
            self.data[:n], row_p, np.asarray(col_p),
            np.asarray(val_p), pos_f,
        )

    # -- KNN --------------------------------------------------------------

    def knn(self, point: np.ndarray, k: int = 1
            ) -> Tuple[np.ndarray, np.ndarray]:
        """k nearest neighbours of ``point`` by best-first traversal
        with cell-distance pruning. Returns (indices, distances),
        nearest first."""
        point = np.asarray(point, np.float64)
        heap: List[Tuple[float, int, QuadTree]] = []
        tiebreak = 0
        heapq.heappush(heap, (0.0, tiebreak, self))
        best: List[Tuple[float, int]] = []  # (-dist_sq, index) max-heap
        while heap:
            bound, _, node = heapq.heappop(heap)
            if len(best) == k and bound > -best[0][0]:
                break
            if node.is_leaf:
                for i in range(node.size):
                    idx = int(node.indices[i])
                    diff = self.data[idx] - point
                    d = float(diff @ diff)
                    if len(best) < k:
                        heapq.heappush(best, (-d, idx))
                    elif d < -best[0][0]:
                        heapq.heapreplace(best, (-d, idx))
            else:
                for c in node._children():
                    if c.cum_size == 0:
                        continue
                    tiebreak += 1
                    heapq.heappush(
                        heap,
                        (c.boundary.min_sq_dist(point), tiebreak, c),
                    )
        out = sorted(((-d, i) for d, i in best))
        idxs = np.asarray([i for _, i in out], np.int64)
        dists = np.sqrt(np.asarray([d for d, _ in out]))
        return idxs, dists
