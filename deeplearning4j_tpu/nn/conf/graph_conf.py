"""ComputationGraph configuration — DAG of vertices (reference:
``nn/conf/ComputationGraphConfiguration.java`` GraphBuilder at ``:398``
(addLayer ``:517``, addInputs ``:553``, setOutputs ``:581``, addVertex
``:597``) and the vertex impls under ``nn/graph/vertex/impl/``).

Vertices are frozen dataclasses like layers; the graph is stored as
``{name: (vertex, input_names)}`` plus input/output name lists, and a
Kahn topological order is computed once at build time (reference
``ComputationGraph.topologicalSortOrder():809``). Execution is a pure
function: walk the topo order, feed a ``{name: array}`` value map —
XLA sees one flat fused program, the DAG bookkeeping disappears at
trace time.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.preprocessors import (
    InputPreProcessor,
    ShapeContext,
)
from deeplearning4j_tpu.nn.layers.base import (
    LayerSpec,
    layer_from_json,
    layer_to_json,
)

VERTEX_REGISTRY: Dict[str, type] = {}


def register_vertex(cls):
    VERTEX_REGISTRY[cls.__name__] = cls
    return cls


@dataclass(frozen=True)
class GraphVertexSpec:
    """Base vertex (reference ``nn/graph/vertex/GraphVertex.java``
    doForward ``:117``; backward is jax.grad)."""

    def apply(self, params, inputs: Sequence, state, *, train=False,
              rng=None, mask=None):
        raise NotImplementedError

    def output_type(self, input_types: Sequence[InputType]) -> InputType:
        return input_types[0]

    def init_params(self, key, dtype=jnp.float32) -> dict:
        return {}

    def init_state(self, dtype=jnp.float32) -> dict:
        return {}

    def layer(self) -> Optional[LayerSpec]:
        return None

    def to_json(self) -> dict:
        d = {"@class": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, LayerSpec):
                v = {"@layer": True, **layer_to_json(v)}
            elif isinstance(v, InputPreProcessor):
                v = {"@preproc": True, **v.to_json()}
            elif isinstance(v, tuple):
                v = list(v)
            d[f.name] = v
        return d

    @staticmethod
    def from_json(d: dict) -> "GraphVertexSpec":
        d = dict(d)
        cls = VERTEX_REGISTRY[d.pop("@class")]
        kwargs = {}
        names = {f.name for f in dataclasses.fields(cls)}
        for k, v in d.items():
            if k not in names:
                continue
            if isinstance(v, dict) and v.get("@layer"):
                v = layer_from_json({
                    kk: vv for kk, vv in v.items() if kk != "@layer"
                })
            elif isinstance(v, dict) and v.get("@preproc"):
                v = InputPreProcessor.from_json({
                    kk: vv for kk, vv in v.items() if kk != "@preproc"
                })
            elif isinstance(v, list):
                v = tuple(v)
            kwargs[k] = v
        return cls(**kwargs)


@register_vertex
@dataclass(frozen=True)
class LayerVertex(GraphVertexSpec):
    """Wraps a layer (+ optional input preprocessor) — reference
    ``nn/graph/vertex/impl/LayerVertex.java``."""

    layer_conf: LayerSpec = None  # type: ignore[assignment]
    preprocessor: Optional[InputPreProcessor] = None

    def layer(self) -> Optional[LayerSpec]:
        return self.layer_conf

    def init_params(self, key, dtype=jnp.float32) -> dict:
        return self.layer_conf.init_params(key, dtype)

    def init_state(self, dtype=jnp.float32) -> dict:
        return self.layer_conf.init_state(dtype)

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None, ctx: Optional[ShapeContext] = None):
        if len(inputs) != 1:
            raise ValueError("LayerVertex expects exactly one input")
        x = inputs[0]
        if self.preprocessor is not None:
            # ``ctx`` is the engine-global shape context (original
            # minibatch batch/time) — a vertex's own input may already
            # be flattened to [b*t, f], from which neither batch nor
            # time is recoverable (MultiLayerNetwork threads its ctx
            # from the original input the same way)
            if ctx is None:
                t = x.shape[2] if x.ndim == 3 else -1
                ctx = ShapeContext(batch=x.shape[0], time=t)
            x = self.preprocessor.preprocess(x, ctx)
        return self.layer_conf.apply(
            params, x, state, train=train, rng=rng, mask=mask
        )

    def output_type(self, input_types):
        it = input_types[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer_conf.output_type(it)


@register_vertex
@dataclass(frozen=True)
class MergeVertex(GraphVertexSpec):
    """Concatenate along the feature axis (reference
    ``MergeVertex.java``): 2-d [b,n], 3-d [b,n,t], 4-d [b,c,h,w] all
    merge on axis 1."""

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None):
        return jnp.concatenate(inputs, axis=1), state

    def output_type(self, input_types):
        it = input_types[0]
        if it.kind == "convolutional":
            return InputType.convolutional(
                it.height, it.width,
                sum(t.channels for t in input_types),
            )
        total = sum(t.size or t.flat_size() for t in input_types)
        if it.kind == "recurrent":
            return InputType.recurrent(total, it.timeseries_length)
        return InputType.feed_forward(total)


@register_vertex
@dataclass(frozen=True)
class ElementWiseVertex(GraphVertexSpec):
    """Add/Subtract/Product/Average/Max of same-shaped inputs
    (reference ``ElementWiseVertex.java``)."""

    op: str = "Add"

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None):
        op = self.op.lower()
        if op == "add":
            out = sum(inputs)
        elif op == "subtract":
            if len(inputs) != 2:
                raise ValueError("Subtract requires exactly 2 inputs")
            out = inputs[0] - inputs[1]
        elif op == "product":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
        elif op == "average":
            out = sum(inputs) / len(inputs)
        elif op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
        else:
            raise ValueError(f"Unknown ElementWise op '{self.op}'")
        return out, state


@register_vertex
@dataclass(frozen=True)
class SubsetVertex(GraphVertexSpec):
    """Feature range [from, to] inclusive (reference
    ``SubsetVertex.java``)."""

    from_idx: int = 0
    to_idx: int = 0

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None):
        return inputs[0][:, self.from_idx:self.to_idx + 1], state

    def output_type(self, input_types):
        n = self.to_idx - self.from_idx + 1
        it = input_types[0]
        if it.kind == "recurrent":
            return InputType.recurrent(n, it.timeseries_length)
        return InputType.feed_forward(n)


@register_vertex
@dataclass(frozen=True)
class L2Vertex(GraphVertexSpec):
    """Pairwise L2 distance between two inputs -> [b, 1] (reference
    ``L2Vertex.java``)."""

    eps: float = 1e-8

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None):
        a, b = inputs
        d = (a - b).reshape(a.shape[0], -1)
        return jnp.sqrt(jnp.sum(d * d, axis=1, keepdims=True) + self.eps), state

    def output_type(self, input_types):
        return InputType.feed_forward(1)


@register_vertex
@dataclass(frozen=True)
class L2NormalizeVertex(GraphVertexSpec):
    """Normalize rows to unit L2 norm (reference
    ``L2NormalizeVertex.java``)."""

    eps: float = 1e-8

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None):
        x = inputs[0]
        flat = x.reshape(x.shape[0], -1)
        norm = jnp.sqrt(jnp.sum(flat * flat, axis=1) + self.eps)
        return x / norm.reshape((-1,) + (1,) * (x.ndim - 1)), state


@register_vertex
@dataclass(frozen=True)
class StackVertex(GraphVertexSpec):
    """Stack along the batch axis (reference ``StackVertex.java``)."""

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None):
        return jnp.concatenate(inputs, axis=0), state


@register_vertex
@dataclass(frozen=True)
class UnstackVertex(GraphVertexSpec):
    """Take slice ``from_idx`` of ``stack_size`` equal batch chunks
    (reference ``UnstackVertex.java``)."""

    from_idx: int = 0
    stack_size: int = 1

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None):
        x = inputs[0]
        n = x.shape[0] // self.stack_size
        return x[self.from_idx * n:(self.from_idx + 1) * n], state


@register_vertex
@dataclass(frozen=True)
class PreprocessorVertex(GraphVertexSpec):
    """Standalone preprocessor vertex (reference
    ``PreprocessorVertex.java``)."""

    preprocessor: InputPreProcessor = None  # type: ignore[assignment]

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None):
        x = inputs[0]
        t = x.shape[2] if x.ndim == 3 else -1
        return self.preprocessor.preprocess(
            x, ShapeContext(batch=x.shape[0], time=t)
        ), state

    def output_type(self, input_types):
        return self.preprocessor.output_type(input_types[0])


@register_vertex
@dataclass(frozen=True)
class ScaleVertex(GraphVertexSpec):
    """Multiply by a fixed scalar (reference ``ScaleVertex.java``)."""

    scale: float = 1.0

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None):
        return inputs[0] * self.scale, state


@register_vertex
@dataclass(frozen=True)
class ShiftVertex(GraphVertexSpec):
    """Add a fixed scalar (reference ``ShiftVertex.java``)."""

    shift: float = 0.0

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None):
        return inputs[0] + self.shift, state


@register_vertex
@dataclass(frozen=True)
class LastTimeStepVertex(GraphVertexSpec):
    """[b, n, t] -> [b, n] taking the last unmasked timestep (reference
    ``nn/graph/vertex/impl/rnn/LastTimeStepVertex.java``)."""

    mask_input: str = ""

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None):
        x = inputs[0]
        if mask is None:
            return x[:, :, -1], state
        # index of last 1 in each row of the [b, t] mask
        t = x.shape[2]
        idx = (t - 1) - jnp.argmax(jnp.flip(mask, axis=1), axis=1)
        return jnp.take_along_axis(
            x, idx.astype(jnp.int32)[:, None, None], axis=2
        )[:, :, 0], state

    def output_type(self, input_types):
        return InputType.feed_forward(input_types[0].size)


@register_vertex
@dataclass(frozen=True)
class DuplicateToTimeSeriesVertex(GraphVertexSpec):
    """[b, n] -> [b, n, t] broadcast over time, t taken from a
    reference input (reference ``DuplicateToTimeSeriesVertex.java``)."""

    reference_input: str = ""

    def apply(self, params, inputs, state, *, train=False, rng=None,
              mask=None, time: int = 1):
        x = inputs[0]
        return jnp.broadcast_to(
            x[:, :, None], x.shape + (time,)
        ), state

    def output_type(self, input_types):
        return InputType.recurrent(input_types[0].size or
                                   input_types[0].flat_size())


# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ComputationGraphConfiguration:
    """Immutable DAG config (reference
    ``ComputationGraphConfiguration.java``)."""

    inputs: Tuple[str, ...]
    outputs: Tuple[str, ...]
    vertices: Dict[str, GraphVertexSpec]
    vertex_inputs: Dict[str, Tuple[str, ...]]
    seed: int = 12345
    iterations: int = 1
    dtype: str = "float32"
    # mixed precision: compute dtype while params stay in ``dtype``
    compute_dtype: Optional[str] = None
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = "Standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    input_types: Optional[Tuple[InputType, ...]] = None
    optimization_algo: str = "STOCHASTIC_GRADIENT_DESCENT"
    max_num_line_search_iterations: int = 5
    # whole-net transform hints (nn/core.py) — runtime knobs, NOT
    # serialized (see MultiLayerConfiguration for rationale)
    scan_layers: bool = False
    remat: str = "none"  # none | dots_saveable | full
    loss_scale: Optional[float] = None  # float16 dynamic loss scaling

    def topological_order(self) -> List[str]:
        """Kahn ordering of vertex names (reference
        ``topologicalSortOrder():809``)."""
        indeg = {name: 0 for name in self.vertices}
        children: Dict[str, List[str]] = {name: [] for name in self.vertices}
        for name, ins in self.vertex_inputs.items():
            for src in ins:
                if src in self.vertices:
                    indeg[name] += 1
                    children[src].append(name)
                elif src not in self.inputs:
                    raise ValueError(
                        f"Vertex '{name}' references unknown input '{src}'"
                    )
        queue = sorted(n for n, d in indeg.items() if d == 0)
        order: List[str] = []
        while queue:
            n = queue.pop(0)
            order.append(n)
            for c in children[n]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    queue.append(c)
        if len(order) != len(self.vertices):
            cyc = set(self.vertices) - set(order)
            raise ValueError(f"Graph has a cycle involving: {sorted(cyc)}")
        return order

    # -- serialization -----------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "format": "deeplearning4j_tpu.ComputationGraphConfiguration",
            "inputs": list(self.inputs),
            "outputs": list(self.outputs),
            "vertices": {n: v.to_json() for n, v in self.vertices.items()},
            "vertex_inputs": {
                n: list(i) for n, i in self.vertex_inputs.items()
            },
            "seed": self.seed,
            "iterations": self.iterations,
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "backprop": self.backprop,
            "pretrain": self.pretrain,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "input_types": (
                [t.to_json() for t in self.input_types]
                if self.input_types else None
            ),
            "optimization_algo": self.optimization_algo,
            "max_num_line_search_iterations":
                self.max_num_line_search_iterations,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration(
            inputs=tuple(d["inputs"]),
            outputs=tuple(d["outputs"]),
            vertices={
                n: GraphVertexSpec.from_json(v)
                for n, v in d["vertices"].items()
            },
            vertex_inputs={
                n: tuple(i) for n, i in d["vertex_inputs"].items()
            },
            seed=d.get("seed", 12345),
            iterations=d.get("iterations", 1),
            dtype=d.get("dtype", "float32"),
            compute_dtype=d.get("compute_dtype"),
            backprop=d.get("backprop", True),
            pretrain=d.get("pretrain", False),
            backprop_type=d.get("backprop_type", "Standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            input_types=(
                tuple(InputType.from_json(t) for t in d["input_types"])
                if d.get("input_types") else None
            ),
            optimization_algo=d.get(
                "optimization_algo", "STOCHASTIC_GRADIENT_DESCENT"
            ),
            max_num_line_search_iterations=d.get(
                "max_num_line_search_iterations", 5
            ),
        )

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))


class GraphBuilder:
    """Reference ``ComputationGraphConfiguration.GraphBuilder``."""

    def __init__(self, parent=None):
        from deeplearning4j_tpu.nn.conf.multi_layer import (
            NeuralNetConfiguration,
        )

        self._parent = parent or NeuralNetConfiguration.Builder()
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._vertices: Dict[str, GraphVertexSpec] = {}
        self._vertex_inputs: Dict[str, Tuple[str, ...]] = {}
        self._input_types: Optional[List[InputType]] = None
        self._backprop = True
        self._pretrain = False
        self._backprop_type = "Standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        for n in names:
            if n in self._inputs or n in self._vertices:
                raise ValueError(f"Duplicate vertex/input name '{n}'")
            self._inputs.append(n)
        return self

    def add_layer(self, name: str, layer: LayerSpec, *inputs: str,
                  preprocessor: Optional[InputPreProcessor] = None
                  ) -> "GraphBuilder":
        self._check_name(name)
        layer = self._parent._resolve_layer(layer)
        self._vertices[name] = LayerVertex(
            layer_conf=layer, preprocessor=preprocessor
        )
        self._vertex_inputs[name] = tuple(inputs)
        return self

    def add_vertex(self, name: str, vertex: GraphVertexSpec,
                   *inputs: str) -> "GraphBuilder":
        self._check_name(name)
        self._vertices[name] = vertex
        self._vertex_inputs[name] = tuple(inputs)
        return self

    def _check_name(self, name: str) -> None:
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"Duplicate vertex/input name '{name}'")

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def backprop(self, b: bool) -> "GraphBuilder":
        self._backprop = b
        return self

    def pretrain(self, p: bool) -> "GraphBuilder":
        self._pretrain = p
        return self

    def backprop_type(self, t: str) -> "GraphBuilder":
        self._backprop_type = t
        return self

    def t_bptt_forward_length(self, n: int) -> "GraphBuilder":
        self._tbptt_fwd = n
        return self

    def t_bptt_backward_length(self, n: int) -> "GraphBuilder":
        self._tbptt_back = n
        return self

    def build(self) -> ComputationGraphConfiguration:
        if not self._inputs:
            raise ValueError("Graph needs addInputs(...)")
        if not self._outputs:
            raise ValueError("Graph needs setOutputs(...)")
        for out in self._outputs:
            if out not in self._vertices:
                raise ValueError(f"Output '{out}' is not a vertex")
        conf = ComputationGraphConfiguration(
            inputs=tuple(self._inputs),
            outputs=tuple(self._outputs),
            vertices=dict(self._vertices),
            vertex_inputs=dict(self._vertex_inputs),
            seed=self._parent._seed,
            iterations=self._parent._iterations,
            dtype=self._parent._dtype,
            compute_dtype=self._parent._compute_dtype,
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            input_types=(
                tuple(self._input_types) if self._input_types else None
            ),
            optimization_algo=getattr(
                self._parent, "_optimization_algo",
                "STOCHASTIC_GRADIENT_DESCENT",
            ),
            max_num_line_search_iterations=getattr(
                self._parent, "_max_num_line_search_iterations", 5
            ),
            scan_layers=getattr(self._parent, "_scan_layers", False),
            remat=getattr(self._parent, "_remat", "none"),
            loss_scale=getattr(self._parent, "_loss_scale", None),
        )
        if self._input_types is not None:
            conf = _infer_shapes(conf)
        conf.topological_order()  # validates acyclicity + references
        return conf


def _infer_shapes(
    conf: ComputationGraphConfiguration,
) -> ComputationGraphConfiguration:
    """Propagate InputTypes through the topo order, filling each layer
    vertex's nIn and auto-inserting shape preprocessors where the
    incoming activation family mismatches the layer family (reference
    ``GraphBuilder.setInputTypes`` + ``addPreProcessors``)."""
    from deeplearning4j_tpu.nn.conf.multi_layer import _auto_preprocessor

    types: Dict[str, InputType] = dict(
        zip(conf.inputs, conf.input_types or ())
    )
    if len(types) != len(conf.inputs):
        raise ValueError("setInputTypes must cover every graph input")
    new_vertices = dict(conf.vertices)
    for name in conf.topological_order():
        v = new_vertices[name]
        in_types = [types[i] for i in conf.vertex_inputs[name]]
        if isinstance(v, LayerVertex):
            it = in_types[0]
            if v.preprocessor is not None:
                it = v.preprocessor.output_type(it)
            else:
                auto = _auto_preprocessor(it, v.layer_conf.input_kind())
                if auto is not None:
                    v = dataclasses.replace(v, preprocessor=auto)
                    it = auto.output_type(it)
            layer = v.layer_conf.with_input_type(it)
            v = dataclasses.replace(v, layer_conf=layer)
            new_vertices[name] = v
        types[name] = v.output_type(in_types)
    return dataclasses.replace(conf, vertices=new_vertices)
