"""Input preprocessors — shape adapters between layer families
(reference: ``nn/conf/preprocessor/*.java``, 13 classes).

Forward-only: backprop through a reshape/transpose is automatic under
``jax.grad`` (the reference hand-writes a ``backprop`` twin per
preprocessor). All are zero-cost under XLA — reshapes/transposes fuse
into neighboring ops.

A ``ShapeContext`` carries the minibatch size and time-series length so
2-d -> 3-d adapters (FeedForwardToRnn) know the time axis; the
reference recovers these from stored ``currentInput`` shapes.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, Type

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

PREPROCESSOR_REGISTRY: Dict[str, Type["InputPreProcessor"]] = {}


def register_preprocessor(cls):
    PREPROCESSOR_REGISTRY[cls.__name__] = cls
    return cls


@dataclass(frozen=True)
class ShapeContext:
    batch: int = 0
    time: int = -1


@dataclass(frozen=True)
class InputPreProcessor:
    def preprocess(self, x, ctx: ShapeContext):
        return x

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def to_json(self) -> dict:
        d = {"@class": type(self).__name__}
        for f in dataclasses.fields(self):
            d[f.name] = getattr(self, f.name)
        return d

    @staticmethod
    def from_json(d: dict) -> "InputPreProcessor":
        d = dict(d)
        cls = PREPROCESSOR_REGISTRY[d.pop("@class")]
        if cls.from_json is not InputPreProcessor.from_json:
            return cls.from_json({"@class": cls.__name__, **d})
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{
            k: (tuple(v) if isinstance(v, list) else v)
            for k, v in d.items() if k in names
        })


@register_preprocessor
@dataclass(frozen=True)
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b, c, h, w] -> [b, c*h*w] (reference
    ``CnnToFeedForwardPreProcessor.java``)."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def preprocess(self, x, ctx):
        return x.reshape(x.shape[0], -1)

    def output_type(self, it: InputType) -> InputType:
        return InputType.feed_forward(it.channels * it.height * it.width)


@register_preprocessor
@dataclass(frozen=True)
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    """[b, c*h*w] -> [b, c, h, w]."""

    height: int = 0
    width: int = 0
    channels: int = 1

    def preprocess(self, x, ctx):
        return x.reshape(x.shape[0], self.channels, self.height, self.width)

    def output_type(self, it: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor
@dataclass(frozen=True)
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b, size, t] -> [b*t, size] (dense layers see one row per
    timestep, reference ``RnnToFeedForwardPreProcessor.java``)."""

    def preprocess(self, x, ctx):
        return jnp.transpose(x, (0, 2, 1)).reshape(-1, x.shape[1])

    def output_type(self, it: InputType) -> InputType:
        return InputType.feed_forward(it.size)


@register_preprocessor
@dataclass(frozen=True)
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[b*t, size] -> [b, size, t]."""

    def preprocess(self, x, ctx):
        t = ctx.time
        return jnp.transpose(
            x.reshape(-1, t, x.shape[-1]), (0, 2, 1)
        )

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(it.size)


@register_preprocessor
@dataclass(frozen=True)
class CnnToRnnPreProcessor(InputPreProcessor):
    """[b, c, h, w] (stacked time along batch) -> [b, c*h*w, t]."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def preprocess(self, x, ctx):
        t = ctx.time
        flat = x.reshape(x.shape[0], -1)  # [b*t, chw]
        return jnp.transpose(flat.reshape(-1, t, flat.shape[-1]), (0, 2, 1))

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(it.channels * it.height * it.width)


@register_preprocessor
@dataclass(frozen=True)
class RnnToCnnPreProcessor(InputPreProcessor):
    """[b, c*h*w, t] -> [b*t, c, h, w]."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def preprocess(self, x, ctx):
        rows = jnp.transpose(x, (0, 2, 1)).reshape(-1, x.shape[1])
        return rows.reshape(-1, self.channels, self.height, self.width)

    def output_type(self, it: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor
@dataclass(frozen=True)
class ReshapePreProcessor(InputPreProcessor):
    """Free-form reshape keeping the batch axis (reference
    ``ReshapePreProcessor.java``)."""

    shape: tuple = ()

    def preprocess(self, x, ctx):
        return x.reshape((x.shape[0],) + tuple(self.shape))


@register_preprocessor
@dataclass(frozen=True)
class ZeroMeanPrePreProcessor(InputPreProcessor):
    def preprocess(self, x, ctx):
        return x - jnp.mean(x, axis=0, keepdims=True)


@register_preprocessor
@dataclass(frozen=True)
class UnitVarianceProcessor(InputPreProcessor):
    def preprocess(self, x, ctx):
        return x / (jnp.std(x, axis=0, keepdims=True) + 1e-8)


@register_preprocessor
@dataclass(frozen=True)
class ComposableInputPreProcessor(InputPreProcessor):
    processors: tuple = ()

    def preprocess(self, x, ctx):
        for p in self.processors:
            x = p.preprocess(x, ctx)
        return x

    def output_type(self, it: InputType) -> InputType:
        for p in self.processors:
            it = p.output_type(it)
        return it

    def to_json(self) -> dict:
        return {
            "@class": type(self).__name__,
            "processors": [p.to_json() for p in self.processors],
        }

    @staticmethod
    def from_json(d: dict) -> "ComposableInputPreProcessor":
        return ComposableInputPreProcessor(
            processors=tuple(
                InputPreProcessor.from_json(p) for p in d["processors"]
            )
        )
