"""Input types for shape inference (reference:
``nn/conf/inputs/InputType.java`` — drives ``setInputType`` auto-config
of nIn and automatic preprocessor insertion).

Shape conventions follow the reference's data layout so iterators and
checkpoints are drop-in compatible:
- feed-forward activations: ``[batch, size]``
- convolutional activations: ``[batch, channels, height, width]`` (NCHW)
- recurrent activations: ``[batch, size, time]``

XLA's TPU layout assignment re-tiles these internally; NCHW at the API
boundary costs nothing after the first fusion.
"""

from __future__ import annotations

from dataclasses import dataclass, asdict


@dataclass(frozen=True)
class InputType:
    kind: str  # "feedforward" | "recurrent" | "convolutional" | "convolutionalFlat"
    size: int = 0  # feedforward / recurrent feature size
    height: int = 0
    width: int = 0
    channels: int = 0
    timeseries_length: int = -1  # -1: unknown/variable

    # -- factories (reference InputType.feedForward etc.) ------------------

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="feedforward", size=int(size))

    @staticmethod
    def recurrent(size: int, timeseries_length: int = -1) -> "InputType":
        return InputType(
            kind="recurrent", size=int(size),
            timeseries_length=int(timeseries_length),
        )

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(
            kind="convolutional", height=int(height), width=int(width),
            channels=int(channels),
        )

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        """Flattened image rows, e.g. MNIST 784 (reference
        InputType.convolutionalFlat)."""
        return InputType(
            kind="convolutionalFlat", height=int(height), width=int(width),
            channels=int(channels), size=int(height * width * channels),
        )

    # -- helpers -----------------------------------------------------------

    def flat_size(self) -> int:
        if self.kind in ("feedforward", "recurrent", "convolutionalFlat"):
            return self.size if self.size else self.height * self.width * self.channels
        return self.channels * self.height * self.width

    def to_json(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_json(d: dict) -> "InputType":
        return InputType(**d)
