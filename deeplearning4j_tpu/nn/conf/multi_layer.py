"""Network configuration DSL (reference:
``nn/conf/NeuralNetConfiguration.java`` Builder/ListBuilder and
``nn/conf/MultiLayerConfiguration.java``).

The builder collects global hyperparameter defaults; ``.list()`` takes
per-layer configs; ``build()`` resolves defaults into each layer (the
reference clones the global conf per layer), runs InputType shape
inference (inferring each layer's nIn and auto-inserting shape
preprocessors — reference ``setInputType`` + ``ConvolutionLayerSetup``),
and produces an immutable, JSON-round-trippable
``MultiLayerConfiguration``. The JSON serves the reference's triple
duty: config DSL output == checkpoint metadata == distribution payload.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
    CnnToRnnPreProcessor,
    FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor,
    InputPreProcessor,
    RnnToCnnPreProcessor,
    RnnToFeedForwardPreProcessor,
)
from deeplearning4j_tpu.nn.layers.base import (
    LayerSpec,
    layer_from_json,
    layer_to_json,
)

# Builder-global fields that flow into every layer that kept its class
# default (reference: per-layer clone of the global conf).
_GLOBAL_LAYER_FIELDS = (
    "activation", "weight_init", "dist", "bias_init", "dropout",
    "drop_connect", "updater", "learning_rate", "bias_learning_rate", "momentum",
    "adam_mean_decay", "adam_var_decay", "rho", "rms_decay", "epsilon",
    "l1", "l2", "gradient_normalization",
    "gradient_normalization_threshold", "lr_policy",
    "lr_policy_decay_rate", "lr_policy_steps", "lr_policy_power",
    "lr_schedule",
)


@dataclass(frozen=True)
class MultiLayerConfiguration:
    """Immutable resolved config (reference
    ``MultiLayerConfiguration``)."""

    layers: Tuple[LayerSpec, ...]
    preprocessors: Dict[int, InputPreProcessor] = field(default_factory=dict)
    seed: int = 12345
    iterations: int = 1
    dtype: str = "float32"
    # mixed precision: forward/backward compute dtype (e.g. "bfloat16"
    # for the MXU) while params/updater state stay in ``dtype`` master
    # precision; None = compute in ``dtype``
    compute_dtype: Optional[str] = None
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = "Standard"  # Standard | TruncatedBPTT
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    input_type: Optional[InputType] = None
    optimization_algo: str = "STOCHASTIC_GRADIENT_DESCENT"
    max_num_line_search_iterations: int = 5
    minimize: bool = True
    # whole-net transform hints (nn/core.py), deliberately NOT
    # serialized: they change the compiled program, never the model
    # semantics, so they stay out of the checkpoint/config identity —
    # a checkpoint trained with scan/remat off restores into a model
    # running them on (and vice versa). Runtime override:
    # ``model.set_transforms(...)``.
    scan_layers: bool = False
    remat: str = "none"  # none | dots_saveable | full
    loss_scale: Optional[float] = None  # float16 dynamic loss scaling

    # -- serialization (parity: conf JSON is the checkpoint schema) --------

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def to_dict(self) -> dict:
        d = {
            "format": "deeplearning4j_tpu.MultiLayerConfiguration",
            "layers": [layer_to_json(l) for l in self.layers],
            "preprocessors": {
                str(i): p.to_json() for i, p in self.preprocessors.items()
            },
            "seed": self.seed,
            "iterations": self.iterations,
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "backprop": self.backprop,
            "pretrain": self.pretrain,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "input_type": self.input_type.to_json() if self.input_type else None,
            "optimization_algo": self.optimization_algo,
            "max_num_line_search_iterations": self.max_num_line_search_iterations,
            "minimize": self.minimize,
        }
        return d

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration(
            layers=tuple(layer_from_json(l) for l in d["layers"]),
            preprocessors={
                int(i): InputPreProcessor.from_json(p)
                for i, p in d.get("preprocessors", {}).items()
            },
            seed=d.get("seed", 12345),
            iterations=d.get("iterations", 1),
            dtype=d.get("dtype", "float32"),
            compute_dtype=d.get("compute_dtype"),
            backprop=d.get("backprop", True),
            pretrain=d.get("pretrain", False),
            backprop_type=d.get("backprop_type", "Standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            input_type=(
                InputType.from_json(d["input_type"]) if d.get("input_type") else None
            ),
            optimization_algo=d.get(
                "optimization_algo", "STOCHASTIC_GRADIENT_DESCENT"
            ),
            max_num_line_search_iterations=d.get(
                "max_num_line_search_iterations", 5
            ),
            minimize=d.get("minimize", True),
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        import yaml

        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml

        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))

    def layer_name(self, i: int) -> str:
        return self.layers[i].name or str(i)


def _auto_preprocessor(
    current: InputType, wanted: str
) -> Optional[InputPreProcessor]:
    """Insert the adapter the reference's InputType machinery would
    (``MultiLayerConfiguration.getPreProcessorForInputType``)."""
    have = current.kind
    if wanted == "any" or have == wanted:
        return None
    if wanted == "feedforward":
        if have == "convolutional":
            return CnnToFeedForwardPreProcessor(
                current.height, current.width, current.channels
            )
        if have == "recurrent":
            return RnnToFeedForwardPreProcessor()
        if have == "convolutionalFlat":
            return None  # already flat rows
    if wanted == "convolutional":
        if have in ("feedforward", "convolutionalFlat"):
            if current.height and current.width:
                return FeedForwardToCnnPreProcessor(
                    current.height, current.width, max(current.channels, 1)
                )
            raise ValueError(
                "Cannot infer CNN input shape from a plain feed-forward "
                "input; use InputType.convolutionalFlat(h, w, c)"
            )
        if have == "recurrent":
            raise ValueError("RnnToCnn requires explicit h/w/c preprocessor")
    if wanted == "recurrent":
        if have in ("feedforward", "convolutionalFlat"):
            return FeedForwardToRnnPreProcessor()
        if have == "convolutional":
            return CnnToRnnPreProcessor(
                current.height, current.width, current.channels
            )
    return None


class ListBuilder:
    """Reference ``NeuralNetConfiguration.ListBuilder``."""

    def __init__(self, parent: "NeuralNetConfiguration.Builder"):
        self._parent = parent
        self._layers: list[LayerSpec] = []
        self._preprocessors: Dict[int, InputPreProcessor] = {}
        self._backprop = True
        self._pretrain = False
        self._backprop_type = "Standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20
        self._input_type: Optional[InputType] = None

    def layer(self, index_or_layer, maybe_layer=None) -> "ListBuilder":
        """Accepts ``.layer(conf)`` or reference-style ``.layer(i, conf)``."""
        if maybe_layer is None:
            self._layers.append(index_or_layer)
        else:
            i = int(index_or_layer)
            while len(self._layers) <= i:
                self._layers.append(None)  # type: ignore[arg-type]
            self._layers[i] = maybe_layer
        return self

    def input_pre_processor(self, i: int, p: InputPreProcessor) -> "ListBuilder":
        self._preprocessors[int(i)] = p
        return self

    def backprop(self, b: bool) -> "ListBuilder":
        self._backprop = b
        return self

    def pretrain(self, p: bool) -> "ListBuilder":
        self._pretrain = p
        return self

    def backprop_type(self, t: str) -> "ListBuilder":
        self._backprop_type = t
        return self

    def t_bptt_forward_length(self, n: int) -> "ListBuilder":
        self._tbptt_fwd = n
        return self

    def t_bptt_backward_length(self, n: int) -> "ListBuilder":
        self._tbptt_back = n
        return self

    def set_input_type(self, it: InputType) -> "ListBuilder":
        self._input_type = it
        return self

    def build(self) -> MultiLayerConfiguration:
        layers = [l for l in self._layers if l is not None]
        resolved = [self._parent._resolve_layer(l) for l in layers]
        preprocessors = dict(self._preprocessors)

        # InputType-driven shape inference + preprocessor insertion
        it = self._input_type
        if it is not None:
            final = []
            for i, layer in enumerate(resolved):
                if i in preprocessors:
                    it = preprocessors[i].output_type(it)
                else:
                    wanted = layer.input_kind()
                    auto = _auto_preprocessor(it, wanted)
                    if auto is not None:
                        preprocessors[i] = auto
                        it = auto.output_type(it)
                layer = layer.with_input_type(it)
                final.append(layer)
                it = layer.output_type(it)
            resolved = final
        else:
            # chain nIn from previous nOut where possible
            final = []
            prev_out: Optional[InputType] = None
            for i, layer in enumerate(resolved):
                if prev_out is not None:
                    if i in preprocessors:
                        prev_out = preprocessors[i].output_type(prev_out)
                    layer = layer.with_input_type(prev_out)
                final.append(layer)
                try:
                    prev_out = layer.output_type(
                        prev_out if prev_out is not None
                        else InputType.feed_forward(getattr(layer, "n_in", 0))
                    )
                except Exception:
                    prev_out = None
            resolved = final

        return MultiLayerConfiguration(
            layers=tuple(resolved),
            preprocessors=preprocessors,
            seed=self._parent._seed,
            iterations=self._parent._iterations,
            dtype=self._parent._dtype,
            compute_dtype=self._parent._compute_dtype,
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            input_type=self._input_type,
            optimization_algo=self._parent._optimization_algo,
            max_num_line_search_iterations=(
                self._parent._max_num_line_search_iterations
            ),
            minimize=self._parent._minimize,
            scan_layers=self._parent._scan_layers,
            remat=self._parent._remat,
            loss_scale=self._parent._loss_scale,
        )


class NeuralNetConfiguration:
    """Namespace mirroring the reference class; use
    ``NeuralNetConfiguration.Builder()``."""

    class Builder:
        def __init__(self):
            self._seed = 12345
            self._iterations = 1
            self._dtype = "float32"
            self._compute_dtype = None
            self._optimization_algo = "STOCHASTIC_GRADIENT_DESCENT"
            self._max_num_line_search_iterations = 5
            self._minimize = True
            self._scan_layers = False
            self._remat = "none"
            self._loss_scale = None
            self._globals: dict = {}

        # -- global hyperparameters (each returns self) --------------------

        def seed(self, s: int):
            self._seed = int(s)
            return self

        def iterations(self, n: int):
            self._iterations = int(n)
            return self

        def data_type(self, dtype: str):
            self._dtype = dtype
            return self

        def compute_data_type(self, dtype):
            """Mixed precision: run forward/backward in ``dtype`` (bf16
            on the MXU) while params/updater state keep the master
            ``data_type``. The TPU-era replacement for the reference's
            all-or-nothing FP16 backend switch (which disabled its cuDNN
            helpers entirely, ``ConvolutionLayer.java:163``)."""
            self._compute_dtype = dtype
            return self

        def scan_layers(self, enabled: bool = True):
            """Whole-net transform hint: run homogeneous layer runs
            under one ``lax.scan`` (O(depth) HLO -> O(1); see
            ``nn/core.py``). Trajectory-neutral; runtime override via
            ``model.set_transforms``."""
            self._scan_layers = bool(enabled)
            return self

        def remat(self, policy: str = "full"):
            """Whole-net transform hint: activation rematerialization
            policy (``none | dots_saveable | full``) — trade recompute
            FLOPs for activation HBM in the backward pass."""
            self._remat = policy
            return self

        def loss_scale(self, scale=True):
            """Dynamic loss scaling for ``compute_data_type("float16")``
            (True = default 2**15 initial scale; a number sets the
            initial scale; None/0 disables). bf16 is unaffected."""
            self._loss_scale = scale
            return self

        def optimization_algo(self, algo: str):
            self._optimization_algo = algo
            return self

        def max_num_line_search_iterations(self, n: int):
            self._max_num_line_search_iterations = int(n)
            return self

        def minimize(self, m: bool):
            self._minimize = m
            return self

        def use_drop_connect(self, use: bool = True):
            """Reference ``Builder.useDropConnect``
            (NeuralNetConfiguration.java:534): route each layer's
            ``dropout`` rate to its WEIGHTS instead of its input."""
            self._globals["drop_connect"] = bool(use)
            return self

        def regularization(self, use: bool):
            # Reference has a boolean master switch; l1/l2 values are
            # simply ignored when off.
            if not use:
                self._globals["l1"] = 0.0
                self._globals["l2"] = 0.0
            return self

        def __getattr__(self, name):
            # Generic global setter for any per-layer field:
            # .activation("relu"), .learning_rate(0.1), .updater("ADAM")...
            if name.startswith("_"):
                raise AttributeError(name)
            snake = name
            if snake in _GLOBAL_LAYER_FIELDS:
                def setter(value):
                    self._globals[snake] = value
                    return self
                return setter
            raise AttributeError(
                f"Unknown builder option '{name}'. Per-layer fields: "
                f"{_GLOBAL_LAYER_FIELDS}"
            )

        def list(self) -> ListBuilder:
            return ListBuilder(self)

        def graph_builder(self):
            """Reference ``NeuralNetConfiguration.Builder.graphBuilder()``."""
            from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder

            return GraphBuilder(self)

        # -- resolution ----------------------------------------------------

        def _resolve_layer(self, layer: LayerSpec) -> LayerSpec:
            """Apply builder globals to fields the layer left at class
            default (reference: global-conf clone + layer override).

            A field whose default the layer *class* deliberately
            redefined (e.g. OutputLayer.activation = "softmax") is
            protected from global override — the user opted into that
            semantic by choosing the layer type.
            """
            updates = {}
            cls = type(layer)
            base_fields = LayerSpec.__dataclass_fields__
            for fname, value in self._globals.items():
                fdef = cls.__dataclass_fields__.get(fname)
                if fdef is None:
                    continue
                current = getattr(layer, fname)
                default = (
                    fdef.default
                    if fdef.default is not dataclasses.MISSING
                    else None
                )
                if current != default:
                    continue  # user set it on the layer instance
                bdef = base_fields.get(fname)
                if bdef is not None and bdef.default is not dataclasses.MISSING:
                    if default != bdef.default:
                        continue  # subclass redefined the default
                updates[fname] = value
            if updates:
                layer = dataclasses.replace(layer, **updates)
            return layer
