"""Configuration package (reference ``nn/conf``)."""

from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf.multi_layer import (  # noqa: F401
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.conf.preprocessors import (  # noqa: F401
    CnnToFeedForwardPreProcessor,
    CnnToRnnPreProcessor,
    ComposableInputPreProcessor,
    FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor,
    InputPreProcessor,
    ReshapePreProcessor,
    RnnToCnnPreProcessor,
    RnnToFeedForwardPreProcessor,
    UnitVarianceProcessor,
    ZeroMeanPrePreProcessor,
)

# Populate the layer registry on conf import
import deeplearning4j_tpu.nn.layers  # noqa: E402,F401

# Graph configuration arrives with the ComputationGraph milestone; kept
# as a late import to avoid a hard dependency cycle.
try:
    from deeplearning4j_tpu.nn.conf.graph_conf import (  # noqa: F401
        ComputationGraphConfiguration,
    )
except ImportError:  # pragma: no cover - before graph milestone
    ComputationGraphConfiguration = None  # type: ignore[assignment]
