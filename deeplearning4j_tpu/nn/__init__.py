"""Neural-network core (reference module: ``deeplearning4j-nn``)."""
