"""The unified functional engine core.

``MultiLayerNetwork`` (sequential stack) and ``ComputationGraph``
(DAG) used to duplicate every hot path: each carried its own jitted
train-step builder, scan-fused multi-step, pretrain step, epoch/fit
drivers, and scan-chunk plumbing — so every performance PR paid its
tax twice. This module is the single implementation both engines wrap:

- **Pure step builders** (``build_step`` / ``build_multi_step`` /
  ``build_pretrain_step``): forward -> loss -> ``jax.value_and_grad``
  -> updater -> (optional) divergence-guard select, telemetry
  grad-norm, dynamic loss scaling — with params/updater-state/state
  donation. An engine contributes only a ``score_fn`` closure (its
  pure forward+loss) and an optional in-jit ``cast`` for the
  cast-on-device input contract.
- **Whole-net transforms**, implemented once and applied through the
  engines' pure forwards:

  * *scan-over-layers* (``detect_layer_runs`` / ``detect_vertex_chains``
    + ``apply_layer_run``): maximal runs of identical, stateless
    layers (transformer blocks, repeated dense groups) have their
    params stacked and the run body traced ONCE under
    ``jax.lax.scan`` — collapsing O(depth) HLO into O(1), which is
    what bounds deep-stack compile time (BENCH r05/r06).
  * *activation rematerialization* (``maybe_remat``): a
    ``none | dots_saveable | full`` policy via ``jax.checkpoint``
    that trades recompute FLOPs for activation HBM, unlocking larger
    batches at fixed peak memory.
  * *dynamic loss scaling* for ``compute_dtype="float16"``
    (``loss_scale_state`` + the ``loss_scale`` step mode): the loss
    is scaled before the backward pass, gradients unscaled after,
    and a non-finite gradient skips the update in-jit and halves the
    scale; ``growth_interval`` clean steps double it back. bf16
    needs none of this (same exponent range as f32) and is unchanged.

- **Fit drivers** (``fit_batches`` / ``fit_epoch_scan`` /
  ``run_scan_chunk`` / ``fit_epochs_device_cached``): the epoch loop,
  scan-chunk grouping, async-dispatch window wiring and listener
  protocol, shared verbatim by both engines.

``scripts/lint_parity.py`` enforces the split: the engine modules may
not re-grow a ``value_and_grad`` / ``lax.scan`` of their own.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# dtype / device helpers (shared cast-on-device contract)
# ---------------------------------------------------------------------------


def dtype_of(conf):
    return jnp.dtype(conf.dtype)


def compute_dtype_of(conf) -> jnp.dtype:
    """Forward/backward compute dtype: ``conf.compute_dtype`` when set
    (mixed precision — bf16/f16 on the MXU with f32 master params),
    else the storage dtype."""
    return jnp.dtype(getattr(conf, "compute_dtype", None) or conf.dtype)


def cast_floats(tree, dtype):
    """Cast floating leaves of a pytree to ``dtype`` (ints — embedding
    indices, native-width inputs — pass through untouched)."""
    return jax.tree_util.tree_map(
        lambda a: (
            a.astype(dtype)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.inexact)
            else a
        ),
        tree,
    )


def to_device(a, dtype):
    """Convert a host array for the jitted step. Integer inputs (e.g.
    uint8 one-hot/pixel data) transfer in their native width and are
    cast to the compute dtype ON DEVICE by the step — 4x less
    host->device traffic than converting to float32 first. Already-
    device-resident arrays pass straight through (no host round
    trip)."""
    if isinstance(a, jax.Array):
        return a.astype(dtype) if a.dtype != dtype else a
    a = np.asarray(a)
    if a.dtype.kind in ("u", "i") and a.dtype.itemsize <= 2:
        return jnp.asarray(a)
    return jnp.asarray(a, dtype)


def cast_stacked(a, dtype):
    """The cast-on-device contract shared by stack_on_device and the
    prestacked-chunk paths of both engines: narrow integers ride at
    native width (the step casts on device); everything else casts to
    the model dtype."""
    return (
        a
        if a.dtype.kind in ("u", "i") and a.dtype.itemsize <= 2
        else a.astype(dtype)
    )


def stack_on_device(arrs, dtype):
    """Stack k same-shaped minibatch arrays for a fused dispatch,
    preserving the cast-on-device contract in ONE place for both
    engines: already-device arrays stack on device (no host round
    trip), narrow integer inputs (uint8 pixels/one-hots) keep their
    native width — the step casts them on device."""
    if all(isinstance(a, jax.Array) for a in arrs):
        return cast_stacked(jnp.stack(arrs), dtype)
    return to_device(np.stack([np.asarray(a) for a in arrs]), dtype)


def nbytes(a) -> int:
    nb = getattr(a, "nbytes", None)
    return int(nb) if nb is not None else int(np.asarray(a).nbytes)


def iter_unchunked(data):
    """Iterate minibatches, expanding any ChunkedDataSet elements
    (streamed pipelines may deliver pre-stacked chunks; consumers
    without a fused path unstack here)."""
    from deeplearning4j_tpu.datasets.api import ChunkedDataSet

    for d in data:
        if isinstance(d, ChunkedDataSet):
            yield from d.to_datasets()
        else:
            yield d


def reg_penalty(layer, layer_params):
    """L1/L2 penalty for one layer (reference calcL1/calcL2)."""
    reg = 0.0
    if layer.l1 > 0.0 or layer.l2 > 0.0:
        for pn in layer.regularizable_params():
            if pn in layer_params:
                w = layer_params[pn]
                if layer.l2 > 0.0:
                    reg = reg + 0.5 * layer.l2 * jnp.sum(w * w)
                if layer.l1 > 0.0:
                    reg = reg + layer.l1 * jnp.sum(jnp.abs(w))
    return reg


# ---------------------------------------------------------------------------
# scan constants (device-resident lr stacks / iteration counter)
# ---------------------------------------------------------------------------


def scan_consts(model, k: int, it0: int):
    """Device-resident (lr_stack, it0) for a fused k-step dispatch.

    Both are tiny, but through a high-latency host link (e.g. the
    tunneled-TPU dev setup) transferring the per-layer lr dict —
    ~n_layers small arrays — EVERY chunk dominated ResNet-50-class
    dispatch cost. Constant schedules (the common case) repeat the
    same values every chunk, so the device copy is cached by value;
    the it0 scalar is reused from the multi-step program's own
    device-computed ``it0 + k`` output (``note_it0``) so steady-state
    chunks transfer nothing host-side at all."""
    rows = [model.updater_def.scheduled_lrs(it0 + i) for i in range(k)]
    names = list(model.updater_def.settings)
    key = (k, tuple(
        tuple(float(r[n]) for n in names) for r in rows
    ))
    cache = model._scan_const_cache
    lr = cache.get(key)
    if lr is None:
        if len(cache) >= 64:  # unbounded only for pathological schedules
            cache.clear()
        lr = {
            n: jnp.asarray([r[n] for r in rows], jnp.float32)
            for n in names
        }
        cache[key] = lr
    if model._it0_shadow == it0 and model._it0_dev is not None:
        it0_dev = model._it0_dev
    else:
        it0_dev = jnp.asarray(it0, jnp.int32)
    return lr, it0_dev


def note_it0(model, it0_dev, host_value: int) -> None:
    """Record the device-side iteration counter a multi-step program
    returned, for reuse by the next chunk's ``scan_consts``."""
    model._it0_dev = it0_dev
    model._it0_shadow = host_value


# ---------------------------------------------------------------------------
# streaming (rnn_time_step) bookkeeping
# ---------------------------------------------------------------------------


def stream_guard_and_prime(named_layers, rnn_state, stream_steps,
                           t_new, batch, dtype) -> None:
    """Shared ``rnn_time_step`` bookkeeping for both engines: raise
    before a finite streaming cache (KV) would silently wrap, and
    prime missing streaming state (zero caches / carries).
    ``named_layers``: (name, layer_conf) pairs."""
    caps = [
        lc.stream_capacity() for _, lc in named_layers
        if lc.streams_state() and lc.stream_capacity()
    ]
    if caps and stream_steps + t_new > min(caps):
        raise ValueError(
            f"rnn_time_step overflow: {stream_steps} + {t_new} "
            f"timesteps exceeds the smallest streaming cache "
            f"({min(caps)}); raise kv_cache or call "
            "rnn_clear_previous_state()"
        )
    for name, lc in named_layers:
        if (
            lc.streams_state()
            and name not in rnn_state
            and getattr(lc, "init_stream_state", None) is not None
        ):
            rnn_state[name] = lc.init_stream_state(batch, dtype)


def extract_stream_state(named_layers, new_state, rnn_state) -> None:
    """Pull each streaming layer's carry keys out of the step's state
    into the host-held ``rnn_state`` (the reference's stateMap)."""
    for name, lc in named_layers:
        if lc.streams_state():
            rnn_state[name] = {
                k: new_state[name][k]
                for k in lc.stream_state_keys()
                if k in new_state[name]
            }


# ---------------------------------------------------------------------------
# whole-net transform: activation rematerialization
# ---------------------------------------------------------------------------

REMAT_POLICIES = ("none", "dots_saveable", "full")


def check_remat_policy(policy: str) -> str:
    if policy not in REMAT_POLICIES:
        raise ValueError(
            f"remat policy must be one of {REMAT_POLICIES}, "
            f"got {policy!r}"
        )
    return policy


def maybe_remat(fn: Callable, policy: str) -> Callable:
    """Wrap ``fn`` in ``jax.checkpoint`` per the remat policy:
    ``"full"`` saves only the inputs (recompute everything in the
    backward pass), ``"dots_saveable"`` keeps matmul/conv outputs (the
    MXU results that are expensive to recompute) and drops the cheap
    elementwise intermediates, ``"none"`` is the identity. The primal
    forward is untouched — only what the backward pass reads changes —
    so outputs (and, op-for-op, gradients) match the unwrapped fn."""
    if policy == "none":
        return fn
    if policy == "full":
        return jax.checkpoint(fn)
    check_remat_policy(policy)
    return jax.checkpoint(
        fn, policy=jax.checkpoint_policies.dots_saveable
    )


# ---------------------------------------------------------------------------
# whole-net transform: scan-over-layers
# ---------------------------------------------------------------------------


def layer_scan_signature(layer) -> str:
    """Config identity for run detection: two layers with equal
    signatures are the SAME program modulo parameter values (the name
    is display-only)."""
    from deeplearning4j_tpu.nn.layers.base import layer_to_json

    d = layer_to_json(layer)
    d.pop("name", None)
    return json.dumps(d, sort_keys=True, default=str)


def scannable_layer(layer) -> bool:
    """A layer may join a scanned run when its per-step program is
    self-contained and stateless: no recurrent/TBPTT carry, no loss
    head, no pretrain phase, no batch statistics, and an empty state
    pytree (BatchNorm's running stats would have to thread through the
    scan carry — excluded instead)."""
    try:
        return bool(
            layer.supports_layer_scan() and not layer.init_state()
        )
    except Exception:
        return False


def detect_layer_runs(layers, preprocessors=None,
                      min_run: int = 2) -> List[Tuple[int, int]]:
    """Maximal runs ``[(start, end))`` of consecutive identical,
    scannable layers in a sequential stack. A preprocessor on an inner
    member breaks the run (its reshape is part of the program); one on
    the head is fine — it applies before the run is entered."""
    pre = preprocessors or {}
    runs: List[Tuple[int, int]] = []
    i, n = 0, len(layers)
    while i < n:
        if not scannable_layer(layers[i]):
            i += 1
            continue
        sig = layer_scan_signature(layers[i])
        j = i + 1
        while (
            j < n
            and j not in pre
            and scannable_layer(layers[j])
            and layer_scan_signature(layers[j]) == sig
        ):
            j += 1
        if j - i >= min_run:
            runs.append((i, j))
        i = max(j, i + 1)
    return runs


def detect_vertex_chains(conf, topo) -> List[Tuple[int, int]]:
    """Scan-over-layers for the DAG engine: maximal linear chains
    ``[(start, end))`` over consecutive TOPO positions where every
    member is a single-input, preprocessor-less LayerVertex with an
    identical scannable layer config, each inner member feeds ONLY the
    next, and no member is an output vertex. (Consecutive topo
    positions keep the per-layer PRNG fold-in indices a contiguous
    range, bitwise-matching the unrolled walk.)"""
    from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex

    consumers: Dict[str, int] = {}
    for name in topo:
        for s in conf.vertex_inputs.get(name, []):
            consumers[s] = consumers.get(s, 0) + 1

    def eligible(name: str) -> bool:
        v = conf.vertices[name]
        return (
            isinstance(v, LayerVertex)
            and v.preprocessor is None
            and name not in conf.outputs
            and len(conf.vertex_inputs.get(name, [])) == 1
            and scannable_layer(v.layer_conf)
        )

    chains: List[Tuple[int, int]] = []
    i, n = 0, len(topo)
    while i < n:
        if not eligible(topo[i]):
            i += 1
            continue
        sig = layer_scan_signature(conf.vertices[topo[i]].layer_conf)
        j = i
        while (
            j + 1 < n
            and eligible(topo[j + 1])
            and tuple(conf.vertex_inputs[topo[j + 1]]) == (topo[j],)
            and consumers.get(topo[j], 0) == 1
            and layer_scan_signature(
                conf.vertices[topo[j + 1]].layer_conf
            ) == sig
        ):
            j += 1
        if j > i:
            chains.append((i, j + 1))
        i = max(j + 1, i + 1)
    return chains


def apply_layer_run(layer, names, params, x, *, train, rng, idx0,
                    mask=None, remat: str = "none"):
    """Apply ``len(names)`` identical layers as ONE ``lax.scan`` over
    their stacked params. The run body is traced once, so the HLO for
    a depth-d run is O(1) instead of O(d) — the compile-time win. The
    per-layer PRNG keys are the same ``fold_in(rng, layer_index)``
    stream the unrolled walk draws, so dropout/DropConnect masks are
    bitwise identical with the transform on or off."""
    pnames = list(params[names[0]])
    stacked = {
        pn: jnp.stack([params[n][pn] for n in names]) for pn in pnames
    }
    k = len(names)
    rngs = None
    if rng is not None:
        rngs = jax.vmap(
            lambda i: jax.random.fold_in(rng, i)
        )(idx0 + jnp.arange(k))

    def body(h, per):
        p, r = per
        y, _ = layer.apply(p, h, {}, train=train, rng=r, mask=mask)
        return y, None

    body = maybe_remat(body, remat if train else "none")
    out, _ = jax.lax.scan(body, x, (stacked, rngs))
    return out


def run_is_ready(names, params, state) -> bool:
    """Trace-time gate for a detected run: params exist (a run of
    param-less layers gives the scan nothing to iterate) and no member
    carries live state (streaming KV caches in ``rnn_time_step`` fall
    back to the unrolled walk)."""
    return bool(params.get(names[0])) and all(
        not state.get(n) for n in names
    )


# ---------------------------------------------------------------------------
# the sequential pure forward (MultiLayerNetwork's apply)
# ---------------------------------------------------------------------------


def sequential_forward(conf, layer_names, params, state, x, *,
                       train: bool, rng, upto: Optional[int] = None,
                       collect: bool = False, fmask=None,
                       scan_layers: bool = False, remat: str = "none",
                       runs: Sequence[Tuple[int, int]] = ()):
    """Pure forward through layers [0, upto]; returns (activation,
    preout of last executed layer, new_state, [activations]).

    ``fmask``: [batch, time] features mask threaded to recurrent
    layers (reference ``setLayerMaskArrays``). ``scan_layers``/
    ``remat``/``runs`` are the whole-net transform knobs — with all
    off this is exactly the classic unrolled walk."""
    from deeplearning4j_tpu.nn.conf.preprocessors import ShapeContext

    cdt = compute_dtype_of(conf)
    if cdt != dtype_of(conf):
        # mixed precision: master params stay in the storage dtype
        # (grads flow back through the cast, so the updater applies
        # them in master precision); compute runs in cdt
        params = cast_floats(params, cdt)
        x = cast_floats(x, cdt)
        fmask = cast_floats(fmask, cdt) if fmask is not None else None
    t = x.shape[2] if x.ndim == 3 else -1
    ctx = ShapeContext(batch=x.shape[0], time=t)
    n = len(conf.layers) if upto is None else upto + 1
    new_state = dict(state)
    acts: List[Any] = []
    preout = None
    # collect/upto need every per-layer activation — runs disabled
    run_at = (
        {s: e for s, e in runs}
        if scan_layers and not collect and upto is None else {}
    )
    rem = remat if train else "none"
    i = 0
    while i < n:
        name = layer_names[i]
        layer = conf.layers[i]
        if i in conf.preprocessors:
            x = conf.preprocessors[i].preprocess(x, ctx)
        end = run_at.get(i)
        if end is not None and end <= n:
            names = layer_names[i:end]
            if run_is_ready(names, params, state):
                x = apply_layer_run(
                    layer, names, params, x, train=train, rng=rng,
                    idx0=i, mask=fmask, remat=rem,
                )
                for rn in names:
                    new_state[rn] = state.get(rn, {})
                i = end
                continue
        if (not train and not collect and i + 1 < n
                and (i + 1) not in conf.preprocessors
                and (i + 1) not in run_at
                and getattr(layer, "kernel_size", None) is not None):
            # inference peephole: Conv(identity) -> BN(act) as ONE
            # fused kernel call (None when the fused path doesn't
            # engage — then the ordinary walk below runs unchanged)
            from deeplearning4j_tpu.nn.layers.convolution import (
                maybe_fused_conv_bn,
            )

            nxt = layer_names[i + 1]
            fused = maybe_fused_conv_bn(
                layer, conf.layers[i + 1], params.get(name, {}),
                params.get(nxt, {}), state.get(nxt, {}), x,
            )
            if fused is not None:
                x = fused
                new_state[name] = state.get(name, {})
                new_state[nxt] = state.get(nxt, {})
                i += 2
                continue
        lrng = jax.random.fold_in(rng, i) if rng is not None else None
        if i == n - 1 and hasattr(layer, "pre_output") and layer.has_loss():
            xin = layer.maybe_dropout(x, train=train, rng=lrng)
            # same lrng as apply -> identical DropConnect mask
            pw = layer.maybe_drop_connect(
                params[name], train=train, rng=lrng
            )
            preout = layer.pre_output(pw, xin)

        def apply_one(p, h, st, *, _layer=layer, _rng=lrng):
            return _layer.apply(
                p, h, st, train=train, rng=_rng, mask=fmask
            )

        if rem != "none" and not layer.has_loss():
            apply_one = maybe_remat(apply_one, rem)
        x, st = apply_one(params[name], x, state.get(name, {}))
        new_state[name] = st
        if collect:
            acts.append(x)
        i += 1
    return x, preout, new_state, acts


def sequential_score(conf, layer_names, params, state, x, labels,
                     mask, rng, *, train: bool, fmask=None,
                     scan_layers: bool = False, remat: str = "none",
                     runs: Sequence[Tuple[int, int]] = ()):
    """Loss score incl. L1/L2 penalty (reference
    computeGradientAndScore adds calcL1/calcL2 to the loss). ``mask``
    is the labels mask (falls back to ``fmask`` for 3-d labels, like
    the reference's output-layer masking)."""
    from deeplearning4j_tpu.nn import losses as losses_mod

    out, preout, new_state, _ = sequential_forward(
        conf, layer_names, params, state, x, train=train, rng=rng,
        fmask=fmask, scan_layers=scan_layers, remat=remat, runs=runs,
    )
    last = conf.layers[-1]
    if not last.has_loss():
        raise ValueError(
            "Last layer has no loss function; use an OutputLayer/LossLayer"
        )
    if preout is None:
        preout = out
    loss_mask = mask
    if loss_mask is None and labels.ndim == 3:
        loss_mask = fmask
    score = losses_mod.score(
        last.loss, labels, preout, last.activation, loss_mask, True
    )
    reg = 0.0
    for lname, layer in zip(layer_names, conf.layers):
        reg = reg + reg_penalty(layer, params[lname])
    return score + reg, new_state


# ---------------------------------------------------------------------------
# dynamic loss scaling (compute_dtype="float16")
# ---------------------------------------------------------------------------

DEFAULT_LOSS_SCALE = 2.0 ** 15
LOSS_SCALE_GROWTH_INTERVAL = 2000
MAX_LOSS_SCALE = 2.0 ** 24


def loss_scale_state(initial: float = DEFAULT_LOSS_SCALE) -> dict:
    """Device-resident dynamic loss-scale state threaded through the
    jitted step: current scale, clean steps since the last change,
    cumulative overflow count (read lazily by telemetry — no per-step
    host sync)."""
    return {
        "scale": jnp.asarray(float(initial), jnp.float32),
        "good_steps": jnp.asarray(0, jnp.int32),
        "overflows": jnp.asarray(0, jnp.int32),
    }


def _scale_tree(tree, factor):
    return jax.tree_util.tree_map(
        lambda g: (
            g * factor.astype(g.dtype)
            if jnp.issubdtype(jnp.asarray(g).dtype, jnp.inexact)
            else g
        ),
        tree,
    )


# ---------------------------------------------------------------------------
# ZeRO-1 optimizer-state sharding (flattened-leaf layout)
# ---------------------------------------------------------------------------
#
# The data-parallel trainer replicates updater state (Adam/RMSProp
# moments) on every device, so its HBM cost is O(params) per chip no
# matter how wide the mesh is. The zero layout instead stores each
# state leaf as a 1-d vector, zero-padded to a multiple of the shard
# count and sharded P("data"): each device holds 1/N of every moment.
# The updater rules are elementwise, so running them on the flat
# vectors is bitwise the canonical-shape math, and the padding slots
# (grad 0, state 0) provably produce step 0 / state 0 under every rule
# — the trajectory is bitwise identical to the replicated baseline.
# Checkpoints/snapshots always store the CANONICAL layout
# (zero_gather_updater_state), so a save on an 8-device mesh restores
# bitwise on 4 or 1.

_ZERO_GATHER_MS = None


def _zero_gather_summary():
    global _ZERO_GATHER_MS
    if _ZERO_GATHER_MS is None:
        from deeplearning4j_tpu.observability.metrics import (
            default_registry,
        )

        _ZERO_GATHER_MS = default_registry().summary(
            "zero_allgather_ms",
            help="host gather of zero-sharded optimizer state back to "
                 "canonical per-param shapes (checkpoint/snapshot/"
                 "re-shard path, ms)",
        )._default()
    return _ZERO_GATHER_MS


def zero_flat_size(shape, shards: int) -> int:
    """Padded flat length of one leaf under the zero layout: the
    element count rounded up to a multiple of the shard count so
    ``P("data")`` splits it evenly."""
    n = int(np.prod(shape)) if len(shape) else 1
    return -(-n // int(shards)) * int(shards)


def zero_flatten_leaf(a, shards: int):
    """Canonical leaf -> flat zero-padded vector (pure; runs in-jit)."""
    v = jnp.reshape(a, (-1,))
    pad = zero_flat_size(a.shape, shards) - v.shape[0]
    if pad:
        v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
    return v


def zero_unflatten_leaf(v, shape):
    """Inverse of ``zero_flatten_leaf``: drop the padding, restore the
    canonical shape."""
    n = int(np.prod(shape)) if len(shape) else 1
    return jnp.reshape(v[:n], shape)


def zero_layout_closures(zero_layout):
    """(flatten, unflatten) for a ``{"shards": n}`` layout, or
    ``(None, None)`` — the pair ``MultiLayerUpdaterDef.update`` takes."""
    if not zero_layout:
        return None, None
    shards = int(zero_layout["shards"])
    return (lambda a: zero_flatten_leaf(a, shards)), zero_unflatten_leaf


def _host_gather_leaf(a):
    """Device->host copy of one (possibly sharded) leaf. A leaf whose
    shards span OTHER processes (zero on a multi-process mesh) is not
    locally readable — replicate it first via a jitted identity, a
    real all-gather collective, which is safe because every caller
    (snapshot push, checkpoint save, re-shard) runs in barrier-kept
    lockstep across ranks."""
    import jax

    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = a.sharding.mesh
        a = jax.jit(
            lambda x: x,
            out_shardings=NamedSharding(mesh, PartitionSpec()),
        )(a)
    return np.asarray(a)


def host_snapshot_tree(tree):
    """Buffer-isolated host copy of a pytree — the ``SnapshotRing``
    copy discipline, shared with checkpoint snapshots: every leaf
    comes back as a fresh ``np.ndarray`` sharing no buffers with the
    input, so the caller may hand the copy to a background thread
    (write-behind checkpointing) or park it in host RAM (snapshot
    ring) while the live tree keeps training. Cross-process-sharded
    leaves ride ``_host_gather_leaf``'s replicating collective, so on
    a multi-process mesh this must run in lockstep across ranks."""
    import jax

    def _copy(a):
        if isinstance(a, np.ndarray):
            return np.array(a)
        return np.asarray(_host_gather_leaf(a))

    return jax.tree_util.tree_map(_copy, tree)


def zero_gather_updater_state(upd_state, params):
    """Gather a zero-laid-out updater state back to canonical
    per-param shapes on HOST (numpy) — the checkpoint / snapshot /
    cross-mesh re-shard form. Idempotent: a leaf already in canonical
    shape passes through (modulo the host copy), so callers may apply
    it without knowing the live layout; ``np.asarray`` on a sharded
    leaf performs the device->host all-gather (cross-process shards
    ride a replicating collective first, see ``_host_gather_leaf``)."""
    t0 = time.perf_counter()
    out: Dict[str, Any] = {}
    for ln, lp in upd_state.items():
        out[ln] = {}
        for pn, tup in lp.items():
            shape = tuple(np.shape(params[ln][pn]))
            n = int(np.prod(shape)) if len(shape) else 1
            gathered = []
            for a in tup:
                h = _host_gather_leaf(a)
                if h.shape != shape:
                    h = h.reshape(-1)[:n].reshape(shape)
                gathered.append(h)
            out[ln][pn] = tuple(gathered)
    _zero_gather_summary().observe(
        (time.perf_counter() - t0) * 1000.0
    )
    return out


# ---------------------------------------------------------------------------
# in-jit gradient accumulation
# ---------------------------------------------------------------------------

_GRAD_ACCUM_GAUGE = None


def note_grad_accum(k: int) -> None:
    """Publish the microbatch count an optimizer step accumulates."""
    global _GRAD_ACCUM_GAUGE
    if _GRAD_ACCUM_GAUGE is None:
        from deeplearning4j_tpu.observability.metrics import (
            default_registry,
        )

        _GRAD_ACCUM_GAUGE = default_registry().gauge(
            "grad_accum_microbatches",
            help="microbatches accumulated in-jit per optimizer step "
                 "(1 = plain single-batch steps)",
        )._default()
    _GRAD_ACCUM_GAUGE.set(float(k))


def _model_layer_confs(model):
    conf = model.conf
    if hasattr(conf, "vertices"):
        return [
            v.layer_conf for v in conf.vertices.values()
            if getattr(v, "layer_conf", None) is not None
        ]
    return list(conf.layers)


def check_grad_accum(model, k) -> int:
    """Validate a ``grad_accum`` knob for ``model``: a positive
    microbatch count, and no batch-statistics layer (each microbatch
    would see its own BatchNormalization stats — different math from
    the full batch, so the config is rejected rather than silently
    diverging)."""
    k = int(k)
    if k < 1:
        raise ValueError(f"grad_accum must be >= 1, got {k}")
    if k > 1 and any(
        layer.uses_batch_statistics()
        for layer in _model_layer_confs(model)
    ):
        raise ValueError(
            "grad_accum > 1 is incompatible with batch-statistics "
            "layers (BatchNormalization): each microbatch would "
            "compute its own batch stats, changing the math vs the "
            "single-big-batch step"
        )
    return k


def set_grad_accum(model, k) -> None:
    """Set the in-jit gradient-accumulation knob on either engine;
    a change invalidates every compiled step that bakes it in."""
    k = check_grad_accum(model, k)
    if k != getattr(model, "grad_accum", 1):
        model.grad_accum = k
        model._jit_step = None
        model._jit_multi_step = None
        model._jit_megastep = None
        if hasattr(model, "_jit_tbptt_multi_step"):
            model._jit_tbptt_multi_step = None
    note_grad_accum(k)


def check_grad_accum_batch(k: int, batch_n: int) -> None:
    if k > 1 and batch_n % k != 0:
        raise ValueError(
            f"grad_accum={k} needs the batch to split into equal "
            f"microbatches; got batch size {batch_n}"
        )


def accum_grad_step(score_fn, params, state, x, labels, mask, fmask,
                    rng, k: int, scale=None,
                    recurrent_names: Sequence[str] = ()):
    """``grad_step`` over K microbatches fused into one program: a
    ``lax.scan`` splits the batch leaves ``[n, ...] -> [k, n/k, ...]``
    (contiguous row blocks — microbatch j is rows ``[j*n/k, (j+1)*
    n/k)``), accumulates f32 gradients + the f32 score, and returns
    their means — ``((score, new_state), grads)``, the same contract
    as ``grad_step``, so one updater apply follows K backward passes
    at one microbatch's activation memory. ``1/k`` is exact for
    power-of-two k; per-microbatch PRNG keys fold the microbatch
    index into ``rng``. Recurrent carry entries are restored per
    microbatch (standard-backprop semantics + a constant scan-carry
    structure), matching ``build_multi_step``."""

    def split(a):
        return jnp.reshape(a, (k, a.shape[0] // k) + a.shape[1:])

    micro = jax.tree_util.tree_map(split, (x, labels, mask, fmask))
    rngs = None
    if rng is not None:
        rngs = jax.vmap(
            lambda j: jax.random.fold_in(rng, j)
        )(jnp.arange(k))
    acc0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params
    )

    def body(carry, per):
        acc, ssum, st = carry
        (xj, yj, mj, fj), rj = per
        (score, new_st), grads = grad_step(
            score_fn, params, st, xj, yj, mj, fj, rj, scale=scale
        )
        new_st = dict(new_st)
        for name in recurrent_names:
            if name in new_st:
                new_st[name] = st[name]
        acc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), acc, grads
        )
        return (acc, ssum + score.astype(jnp.float32), new_st), None

    (acc, ssum, last_state), _ = jax.lax.scan(
        body, (acc0, jnp.zeros((), jnp.float32), state),
        (micro, rngs),
    )
    inv = 1.0 / k
    grads = jax.tree_util.tree_map(
        lambda a, p: (a * inv).astype(jnp.asarray(p).dtype),
        acc, params,
    )
    return (ssum * inv, last_state), grads


# ---------------------------------------------------------------------------
# jitted step builders (ONE implementation for both engines)
# ---------------------------------------------------------------------------


def grad_step(score_fn, params, state, x, labels, mask, fmask, rng,
              scale=None):
    """The forward+backward half every step flavor shares:
    ``((score, new_state), grads)`` of the engine's pure score. With
    ``scale`` (dynamic loss scaling) the loss is scaled in f32 before
    the backward pass so small f16 gradients stay representable; the
    caller unscales."""
    def loss_fn(p):
        s, new_state = score_fn(p, state, x, labels, mask, fmask, rng)
        if scale is not None:
            s = s.astype(jnp.float32) * scale
        return s, new_state

    return jax.value_and_grad(loss_fn, has_aux=True)(params)


def finish_step(updater, grads, score, new_state, params, upd_state,
                state, lrs, t, *, guarded: bool, telemetry: bool,
                ls=None, flatten=None, unflatten=None,
                sg=None, sg_cfg=None):
    """The post-gradient half shared by the engine steps AND the
    distributed trainer's shard_map/GSPMD steps: dynamic loss-scale
    unscale/adjust (when ``ls``, the incoming loss-scale state dict,
    is given — the caller already scaled the loss via ``grad_step``'s
    ``scale``), updater application (optionally through the zero
    flattened-leaf layout via ``flatten``/``unflatten``), optional
    telemetry grad-norm, optional in-jit divergence-guard select —
    statistical when ``sg``/``sg_cfg`` (the incoming EWMA state dict
    + its ``StatGuardConfig``) ride along: a finite-but-anomalous
    loss or grad-norm is suppressed by the SAME select.
    Returns the step output tuple
    ``(params, upd_state, state, score[, grad_norm]
    [, loss_scale_state][, stat_guard_state][, ok])``."""
    from deeplearning4j_tpu.resilience.guard import (
        divergence_ok,
        grad_global_norm_sq,
        select_updates,
        stat_guard_update,
    )

    tail = ()
    if ls is not None:
        scale = ls["scale"]
        inv = 1.0 / scale
        grads = _scale_tree(grads, inv)
        score = score * inv
        # the overflow probe: a non-finite gradient skips the update
        # in-jit and halves the scale; growth_interval clean steps
        # double it back (capped)
        finite = jnp.isfinite(grad_global_norm_sq(grads))
        new_params, new_upd = updater.update(
            grads, upd_state, params, lrs, t,
            flatten=flatten, unflatten=unflatten,
        )
        new_params, new_upd, new_state = select_updates(
            finite, new_params, params, new_upd, upd_state,
            new_state, state,
        )
        good = jnp.where(finite, ls["good_steps"] + 1, 0)
        grow = good >= LOSS_SCALE_GROWTH_INTERVAL
        new_scale = jnp.where(
            finite,
            jnp.where(
                grow,
                jnp.minimum(scale * 2.0, MAX_LOSS_SCALE),
                scale,
            ),
            jnp.maximum(scale * 0.5, 1.0),
        )
        tail = ({
            "scale": new_scale,
            "good_steps": jnp.where(grow, 0, good),
            "overflows": ls["overflows"]
            + (1 - finite.astype(jnp.int32)),
        },)
    else:
        new_params, new_upd = updater.update(
            grads, upd_state, params, lrs, t,
            flatten=flatten, unflatten=unflatten,
        )
    extras = ()
    gnorm = None
    if telemetry:
        gnorm = jnp.sqrt(grad_global_norm_sq(grads))
        extras = (gnorm,)
    if not guarded:
        return (new_params, new_upd, new_state, score) + extras + tail
    ok = divergence_ok(score, grads)
    sg_tail = ()
    if sg is not None:
        if gnorm is None:
            gnorm = jnp.sqrt(grad_global_norm_sq(grads))
        sg_ok, new_sg = stat_guard_update(sg, sg_cfg, score, gnorm, ok)
        ok = jnp.logical_and(ok, sg_ok)
        sg_tail = (new_sg,)
    new_params, new_upd, new_state = select_updates(
        ok, new_params, params, new_upd, upd_state, new_state, state,
    )
    return (
        (new_params, new_upd, new_state, score)
        + extras + tail + sg_tail + (ok,)
    )


def build_step(score_fn, updater, *, cast=None, guarded: bool = False,
               telemetry: bool = False, loss_scale: bool = False,
               grad_accum: int = 1,
               recurrent_names: Sequence[str] = (),
               zero_layout=None, stat_guard=None) -> Callable:
    """ONE jitted SGD train step for both engines.

    ``score_fn(params, state, x, labels, mask, fmask, rng) ->
    (score, new_state)`` is the engine's pure forward+loss; ``cast``
    is its in-jit cast-on-device hook (integer inputs ride in native
    width and cast here). Step output layout:
    ``params, upd_state, state, score[, grad_norm][, loss_scale_state]
    [, ok]`` — unpacked by ``apply_step_out``. With ``loss_scale``
    the step takes the loss-scale state dict as a trailing argument,
    skips the update in-jit on a non-finite gradient (the overflow
    probe), and adjusts the scale — no host round trip. With
    ``grad_accum=K`` the forward/backward runs as a ``lax.scan`` over
    K microbatches (``accum_grad_step``) before the ONE updater apply.
    ``zero_layout`` (``{"shards": n}``) runs the updater through the
    zero flattened-leaf layout — ``upd_state`` leaves are 1-d padded
    vectors (see the ZeRO section above). ``stat_guard`` (a
    ``StatGuardConfig``; requires ``guarded``) threads the statistical
    anomaly guard's EWMA state as a further trailing argument, after
    the loss-scale state."""
    if stat_guard is not None and not guarded:
        raise ValueError(
            "stat_guard requires guarded=True (it shares the "
            "divergence guard's in-jit select and ok flag)"
        )
    flatten, unflatten = zero_layout_closures(zero_layout)
    k = int(grad_accum)

    def step(params, upd_state, state, x, labels, mask, fmask, lrs, t,
             rng, *ls_args):
        if cast is not None:
            x, labels, mask, fmask = cast(x, labels, mask, fmask)
        ls = ls_args[0] if loss_scale else None
        sg = (
            ls_args[1 if loss_scale else 0]
            if stat_guard is not None else None
        )
        scale = ls["scale"] if loss_scale else None
        if k > 1:
            (score, new_state), grads = accum_grad_step(
                score_fn, params, state, x, labels, mask, fmask, rng,
                k, scale=scale, recurrent_names=recurrent_names,
            )
        else:
            (score, new_state), grads = grad_step(
                score_fn, params, state, x, labels, mask, fmask, rng,
                scale=scale,
            )
        return finish_step(
            updater, grads, score, new_state, params, upd_state,
            state, lrs, t, guarded=guarded, telemetry=telemetry,
            ls=ls, flatten=flatten, unflatten=unflatten,
            sg=sg, sg_cfg=stat_guard,
        )

    return jax.jit(step, donate_argnums=(0, 1, 2))


def apply_step_out(model, out):
    """Unpack one core step's output tuple (base 4 fields, plus the
    optional telemetry grad-norm, loss-scale state, stat-guard state,
    and guard ok flag) into model state; returns ``(score, ok)``."""
    model.params, model.updater_state, model.state = out[:3]
    score = out[3]
    i = 4
    if getattr(model, "_telemetry_grad_norm", False):
        model._last_grad_norm = out[i]
        i += 1
    if getattr(model, "_loss_scale_active", False):
        model._loss_scale_state = out[i]
        i += 1
    if stat_guard_active(model):
        model._stat_guard_state = out[i]
        i += 1
    ok = (
        out[i] if getattr(model, "divergence_guard", None) is not None
        else None
    )
    return score, ok


def build_multi_step(score_fn, updater, *, cast,
                     recurrent_names: Sequence[str] = (),
                     tbptt: bool = False, grad_accum: int = 1,
                     zero_layout=None) -> Callable:
    """k optimizer steps fused into ONE XLA program via lax.scan.

    The reference dispatches one native-op sequence per minibatch
    (SURVEY.md §3.1 hot loop); the per-dispatch latency is what bounds
    small-model throughput on TPU (host->device hop per step).
    Scanning k steps amortizes it k-fold: per-step PRNG keys and
    Adam's t are computed on device, lr schedules stay host-side
    (arbitrary Python) and ride in as a tiny stacked array.

    Standard mode restores the recurrent carry per minibatch
    (standard-backprop semantics). ``tbptt=True`` instead THREADS the
    carry through the scan and takes a per-step ``resets`` flag (one
    0/1 per step) that zeroes the carry at minibatch boundaries, so
    MANY minibatches' TBPTT chunk stacks ride in a single dispatch
    (the reference's host-side chunk loop, ``doTruncatedBPTT:1210``,
    pays a dispatch per chunk).

    ``grad_accum``/``zero_layout`` compose exactly as in
    ``build_step``: each scanned optimizer step accumulates K
    microbatch gradients, and the updater runs through the zero
    flattened-leaf layout (TBPTT mode excludes grad_accum — the
    recurrent carry threads BETWEEN chunks, so a chunk cannot split
    into independent microbatches)."""
    flatten, unflatten = zero_layout_closures(zero_layout)
    k_accum = int(grad_accum)
    if tbptt and k_accum > 1:
        raise ValueError(
            "grad_accum > 1 is incompatible with the fused TBPTT "
            "path: the recurrent carry threads between chunks"
        )

    def body(carry, per_step):
        params, upd_state, state = carry
        if tbptt:
            x, labels, mask, fmask, lrs, t, rng, reset = per_step
        else:
            x, labels, mask, fmask, lrs, t, rng = per_step
        x, labels, mask, fmask = cast(x, labels, mask, fmask)
        if tbptt:
            state = dict(state)
            keep = 1.0 - reset
            for name in recurrent_names:
                # reset==1 at a new minibatch's first chunk; v*0 is
                # bitwise the zeros the primed initial state holds
                state[name] = {
                    k2: v * keep.astype(v.dtype)
                    for k2, v in state[name].items()
                }
        if k_accum > 1:
            (score, new_state), grads = accum_grad_step(
                score_fn, params, state, x, labels, mask, fmask, rng,
                k_accum, recurrent_names=recurrent_names,
            )
        else:
            (score, new_state), grads = grad_step(
                score_fn, params, state, x, labels, mask, fmask, rng
            )
        new_params, new_upd = updater.update(
            grads, upd_state, params, lrs, t,
            flatten=flatten, unflatten=unflatten,
        )
        if not tbptt:
            # standard-backprop semantics: recurrent carry resets per
            # minibatch — keep the carry structure constant by
            # restoring the empty input entries
            for name in recurrent_names:
                new_state[name] = state[name]
        return (new_params, new_upd, new_state), score

    def multi_step(params, upd_state, state, xs, ys, masks, fmasks,
                   lr_stack, it0, base_key, *resets):
        k = jax.tree_util.tree_leaves(xs)[0].shape[0]
        ts = (it0 + 1 + jnp.arange(k)).astype(jnp.float32)
        rngs = jax.vmap(
            lambda i: jax.random.fold_in(base_key, i)
        )(it0 + jnp.arange(k))
        (params, upd_state, state), scores = jax.lax.scan(
            body, (params, upd_state, state),
            (xs, ys, masks, fmasks, lr_stack, ts, rngs) + resets,
        )
        # next chunk's it0, computed on device: the caller keeps it
        # resident so consecutive chunks transfer no host scalars
        return params, upd_state, state, scores, it0 + k

    return jax.jit(multi_step, donate_argnums=(0, 1, 2))


# ---------------------------------------------------------------------------
# the megastep executor: K full train steps + metric accumulation in
# ONE XLA dispatch
# ---------------------------------------------------------------------------
#
# build_multi_step fuses k steps but only for the bare step flavor
# (no guard / telemetry / loss scale / stat guard — _can_scan_steps
# refuses those configs). The megastep generalizes it: the scanned
# body is the FULL build_step body (grad_step/accum_grad_step +
# finish_step), so divergence-guard selects, the statistical guard's
# EWMA state, and the dynamic loss-scale state all thread through the
# scan carry, and the chunk's metrics (per-step scores, grad norms,
# guard ok flags, plus their on-device aggregates) come back in ONE
# readback instead of K host syncs. Because each scanned step is the
# same math as the per-step program, the trajectory is bitwise equal
# to the per-step loop (tier-1-asserted on both engines).


def build_megastep(score_fn, updater, *, cast,
                   recurrent_names: Sequence[str] = (),
                   guarded: bool = False, telemetry: bool = False,
                   loss_scale: bool = False, stat_guard=None,
                   grad_accum: int = 1, zero_layout=None,
                   flatten=None, unflatten=None,
                   jit: bool = True) -> Callable:
    """K optimizer steps fused into ONE XLA program, full step flavor.

    Signature of the returned function::

        megastep(params, upd_state, state, xs, ys, masks, fmasks,
                 lr_stack, it0, base_key[, ls_state][, sg_state])
        -> (params, upd_state, state, metrics, it0 + k)
           [+ (ls_state,)][+ (sg_state,)]

    ``metrics`` is the on-device accumulator dict read back once per
    chunk by ``megastep_readback``: ``scores`` [k], ``loss_sum``,
    ``examples``, plus ``grad_norms`` [k] under ``telemetry`` and
    ``oks`` [k] / ``guard_trips`` under ``guarded``. Per-step rng is
    ``fold_in(base_key, it0 + i)`` and Adam's t is ``it0 + 1 + i`` —
    identical to the per-step loop, so the trajectory is bitwise.
    ``flatten``/``unflatten`` override the ``zero_layout`` closures
    (the GSPMD trainer passes sharding-pinned ones); ``jit=False``
    returns the raw function for the trainer to wrap with explicit
    in/out shardings."""
    if stat_guard is not None and not guarded:
        raise ValueError(
            "stat_guard requires guarded=True (it shares the "
            "divergence guard's in-jit select and ok flag)"
        )
    if flatten is None and unflatten is None:
        flatten, unflatten = zero_layout_closures(zero_layout)
    k_accum = int(grad_accum)

    def body(carry, per_step):
        params, upd_state, state, ls, sg = carry
        x, labels, mask, fmask, lrs, t, rng = per_step
        if cast is not None:
            x, labels, mask, fmask = cast(x, labels, mask, fmask)
        scale = ls["scale"] if loss_scale else None
        if k_accum > 1:
            (score, new_state), grads = accum_grad_step(
                score_fn, params, state, x, labels, mask, fmask, rng,
                k_accum, scale=scale,
                recurrent_names=recurrent_names,
            )
        else:
            (score, new_state), grads = grad_step(
                score_fn, params, state, x, labels, mask, fmask, rng,
                scale=scale,
            )
        # standard-backprop semantics: recurrent carry resets per
        # minibatch; restoring the (empty) input entries BEFORE the
        # guard select keeps the carry structure constant
        new_state = dict(new_state)
        for name in recurrent_names:
            if name in new_state:
                new_state[name] = state[name]
        out = finish_step(
            updater, grads, score, new_state, params, upd_state,
            state, lrs, t, guarded=guarded, telemetry=telemetry,
            ls=ls if loss_scale else None,
            flatten=flatten, unflatten=unflatten,
            sg=sg if stat_guard is not None else None,
            sg_cfg=stat_guard,
        )
        new_params, new_upd, new_state, score = out[:4]
        i = 4
        per_out = {"score": score}
        if telemetry:
            per_out["grad_norm"] = out[i]
            i += 1
        new_ls = ls
        if loss_scale:
            new_ls = out[i]
            i += 1
        new_sg = sg
        if stat_guard is not None:
            new_sg = out[i]
            i += 1
        if guarded:
            per_out["ok"] = out[i]
        return (new_params, new_upd, new_state, new_ls, new_sg), per_out

    def megastep(params, upd_state, state, xs, ys, masks, fmasks,
                 lr_stack, it0, base_key, *extra):
        leaf = jax.tree_util.tree_leaves(xs)[0]
        k, rows = leaf.shape[0], leaf.shape[1]
        ts = (it0 + 1 + jnp.arange(k)).astype(jnp.float32)
        rngs = jax.vmap(
            lambda i: jax.random.fold_in(base_key, i)
        )(it0 + jnp.arange(k))
        i = 0
        ls = None
        if loss_scale:
            ls = extra[i]
            i += 1
        sg = extra[i] if stat_guard is not None else None
        (params, upd_state, state, ls, sg), per = jax.lax.scan(
            body, (params, upd_state, state, ls, sg),
            (xs, ys, masks, fmasks, lr_stack, ts, rngs),
        )
        scores = per["score"]
        metrics = {
            "scores": scores,
            "loss_sum": jnp.sum(scores.astype(jnp.float32)),
            "examples": jnp.asarray(k * rows, jnp.int32),
        }
        if telemetry:
            metrics["grad_norms"] = per["grad_norm"]
        if guarded:
            oks = per["ok"]
            metrics["oks"] = oks
            metrics["guard_trips"] = jnp.sum(1 - oks.astype(jnp.int32))
        tail = ()
        if loss_scale:
            tail += (ls,)
        if stat_guard is not None:
            tail += (sg,)
        return (params, upd_state, state, metrics, it0 + k) + tail

    if not jit:
        return megastep
    return jax.jit(megastep, donate_argnums=(0, 1, 2))


_MEGASTEP_GAUGE = None
_MEGASTEP_DISPATCHES = None
_MEGASTEP_READBACK_MS = None


def note_megastep(k: int) -> None:
    """Publish one fused megastep dispatch covering ``k`` steps."""
    global _MEGASTEP_GAUGE, _MEGASTEP_DISPATCHES
    if _MEGASTEP_GAUGE is None:
        from deeplearning4j_tpu.observability.metrics import (
            default_registry,
        )

        reg = default_registry()
        _MEGASTEP_GAUGE = reg.gauge(
            "megastep_chunk_size",
            help="optimizer steps fused into the last megastep "
                 "dispatch (K; trailing partial blocks show smaller)",
        )._default()
        _MEGASTEP_DISPATCHES = reg.counter(
            "megastep_dispatches_total",
            help="fused megastep dispatches executed (steps/dispatch "
                 "= iteration delta / this delta)",
        )._default()
    _MEGASTEP_GAUGE.set(float(k))
    _MEGASTEP_DISPATCHES.inc()


def megastep_readback(metrics):
    """THE designated host-readback site of the megastep path: one
    device->host transfer of the chunk's accumulated metric dict.
    ``scripts/lint_parity.py`` forbids every other host read inside
    the per-chunk driver (``run_megastep_chunk`` /
    ``fit_epoch_megastep``), so the host never re-enters the hot loop
    between dispatches."""
    global _MEGASTEP_READBACK_MS
    if _MEGASTEP_READBACK_MS is None:
        from deeplearning4j_tpu.observability.metrics import (
            default_registry,
        )

        _MEGASTEP_READBACK_MS = default_registry().summary(
            "megastep_readback_ms",
            help="per-chunk device->host readback of the megastep "
                 "metric accumulator (ms; one per K fused steps)",
        )._default()
    t0 = time.perf_counter()
    host = jax.device_get(metrics)
    _MEGASTEP_READBACK_MS.observe((time.perf_counter() - t0) * 1000.0)
    return host


def build_pretrain_step(layer, name: str, upd_def) -> Callable:
    """Jitted single-layer pretrain update; takes the layer's input
    tensor precomputed (the frozen lower stack runs once per batch,
    not once per optimizer iteration — reference feedForwardToLayer
    once per batch). Shared verbatim by both engines."""

    def step(lparams, upd_state, xin, lrs, t, rng):
        def loss_fn(p):
            return layer.pretrain_loss(p, xin, rng) + reg_penalty(
                layer, p
            )

        loss, grads = jax.value_and_grad(loss_fn)(lparams)
        new_p, new_upd = upd_def.update(
            {name: grads}, upd_state, {name: lparams}, lrs, t
        )
        return new_p[name], new_upd, loss

    return jax.jit(step, donate_argnums=(0, 1))


# ---------------------------------------------------------------------------
# fit drivers (epoch loop / scan-chunk grouping / device-cached epochs)
# ---------------------------------------------------------------------------


def build_scan_plan(seq, sig_fn, stack_fn, scan_chunk: int):
    """Group consecutive same-signature minibatches into fused chunks
    (the same boundaries ``fit_epoch_scan`` produces). Returns a list
    of ``("chunk", stacked_device_arrays, last_host_batch)`` /
    ``("single", ds, ds)`` entries, shared by MultiLayerNetwork and
    ComputationGraph."""
    plan: List[Any] = []
    buf: List[Any] = []
    sig = None

    def flush(batches):
        if len(batches) == 1:
            plan.append(("single", batches[0], batches[0]))
        elif batches:
            plan.append(("chunk", stack_fn(batches), batches[-1]))

    for ds in seq:
        s = sig_fn(ds)
        if buf and (s != sig or len(buf) >= scan_chunk):
            flush(buf)
            buf = []
        sig = s
        buf.append(ds)
    flush(buf)
    return plan


def cached_epoch_plan(model, iterator, epochs: int, arrays_of):
    """Shared eligibility gate + HBM size accounting + plan building
    for the device-cached multi-epoch fit path (MultiLayerNetwork and
    ComputationGraph). ``arrays_of(ds)`` yields every array the stacked
    chunks will hold. Returns the scan plan, or None when the caller
    must stream (single epoch, iterator input, non-scannable config, or
    dataset larger than ``model.device_cache_bytes``)."""
    if (
        epochs <= 1
        or not isinstance(iterator, (list, tuple))
        or len(iterator) == 0
        or not model._can_scan_steps()
        or model.scan_chunk <= 1
    ):
        return None
    total = 0
    for ds in iterator:
        if not hasattr(ds, "features"):
            return None
        for a in arrays_of(ds):
            if a is not None:
                total += nbytes(a)
    if total > model.device_cache_bytes:
        return None
    return build_scan_plan(
        iterator, model._ds_scan_sig, model._stack_chunk,
        model.scan_chunk,
    )


def _wants_last_features(model) -> bool:
    fn = getattr(model, "_wants_last_features", None)
    return bool(fn()) if fn is not None else False


def _chunk_rows(xs) -> int:
    """Rows per sub-step of a stacked [k, b, ...] chunk payload."""
    leaf = jax.tree_util.tree_leaves(xs)[0]
    return int(leaf.shape[1]) if getattr(leaf, "ndim", 0) > 1 else 0


def run_scan_chunk(model, stacked) -> None:
    """One fused k-step dispatch from pre-stacked device arrays
    ``(x, y, labels_mask, features_mask, k)`` — the same driver for
    both engines (the arrays are plain arrays for the sequential
    engine, lists for the DAG engine)."""
    from deeplearning4j_tpu.observability import profiler as _prof_mod

    xs, ys, masks, fmasks, k = stacked
    it0 = model.iteration_count
    prof = _prof_mod.get_active_profiler()
    if prof is not None:
        # one fused dispatch = one profiler "step" covering k
        # optimizer steps (the record carries the final step index)
        prof.begin_step(it0 + k)
    lr_stack, it0_dev = scan_consts(model, k, it0)
    if model._jit_multi_step is None:
        model._jit_multi_step = model._build_multi_step()
    (
        model.params, model.updater_state, model.state, scores,
        it0_next,
    ) = model._jit_multi_step(
        model.params, model.updater_state, model.state,
        xs, ys, masks, fmasks, lr_stack, it0_dev, model._base_key,
    )
    note_it0(model, it0_next, it0 + k)
    model.iteration_count += k
    model._last_score = scores[-1]
    if model.listeners:
        lt0 = time.perf_counter()
        for i in range(k):
            model._last_score = scores[i]
            for listener in model.listeners:
                listener.iteration_done(model, it0 + i + 1)
        model._last_score = scores[-1]
        if prof is not None:
            prof.note_listener_ms((time.perf_counter() - lt0) * 1e3)
    if prof is not None:
        # no per-chunk cost model: the fused multi-step program has
        # its own HLO — decomposition + record only
        prof.end_step(score=model._last_score,
                      rows=k * _chunk_rows(xs))


def flush_scan_chunk(model, batches: List[Any]) -> None:
    if len(batches) == 1:
        model.fit_minibatch(batches[0])
        return
    if _wants_last_features(model):
        model._last_features = batches[-1].features
    run_scan_chunk(model, model._stack_chunk(batches))


def fit_epoch_scan(model, it) -> int:
    """Buffer same-shaped minibatches into chunks of
    ``model.scan_chunk`` and run each chunk as one fused dispatch.
    ``ChunkedDataSet`` items (pre-stacked [k, b, ...] payloads from
    an input pipeline) feed the dispatch directly."""
    from deeplearning4j_tpu.datasets.api import ChunkedDataSet

    from deeplearning4j_tpu.parallel import control_plane
    from deeplearning4j_tpu.resilience import preemption

    model._reset_recurrent_state()  # scan carries empty rnn entries
    buf: List[Any] = []
    sig = None
    n = 0
    for ds in it:
        # chunk boundary is the scan path's step boundary: an
        # un-flushed buffer holds no dispatched work, so an emergency
        # checkpoint here is consistent at the last flushed step
        preemption.check_fit(model)
        control_plane.check_fit(model)
        if isinstance(ds, ChunkedDataSet):
            if buf:
                flush_scan_chunk(model, buf)
                buf, sig = [], None
            model._run_prestacked_chunk(ds)
            n += ds.k
            continue
        s = model._ds_scan_sig(ds)
        if buf and s != sig:
            flush_scan_chunk(model, buf)
            buf = []
        sig = s
        buf.append(ds)
        n += 1
        if len(buf) >= model.scan_chunk:
            flush_scan_chunk(model, buf)
            buf = []
    if buf:
        flush_scan_chunk(model, buf)
    return n


# ---------------------------------------------------------------------------
# megastep epoch driver (K steps / dispatch, one readback / chunk)
# ---------------------------------------------------------------------------


def megastep_active(model) -> bool:
    """True when the ``megastep`` knob asks for fused K-step
    dispatches (K > 1)."""
    return int(getattr(model, "megastep", 1) or 1) > 1


def can_megastep(model) -> bool:
    """Megastep eligibility. Unlike ``_can_scan_steps`` the fused
    chunk here runs the FULL step flavor, so divergence guard,
    telemetry, stat guard, and dynamic loss scaling all stay eligible
    (their state threads through the scan carry). Still refused:
    TBPTT (host-side carry between chunks), non-SGD algorithms,
    recurrent models (conservative — per-step semantics preserved via
    fallback), a ROLLBACK-policy guard (its host restore must
    interrupt the trajectory mid-chunk, which a fused dispatch cannot
    do), row-sharded embeddings (the K-step scan carry would bake the
    ``P("data", None)`` table layout into a program the megastep
    cache/AOT identity doesn't key on — per-step dispatch preserves
    semantics), and listeners that neither declare
    ``supports_batched_iterations`` nor implement ``chunk_done``."""
    from deeplearning4j_tpu.resilience.guard import ROLLBACK

    if not megastep_active(model):
        return False
    if has_row_sharded_embedding(model):
        return False
    conf = model.conf
    guard = getattr(model, "divergence_guard", None)
    return (
        getattr(conf, "iterations", 1) == 1
        and bool(getattr(conf, "backprop", True))
        and getattr(conf, "backprop_type", None) != "TruncatedBPTT"
        and getattr(
            conf, "optimization_algo", "STOCHASTIC_GRADIENT_DESCENT"
        ) == "STOCHASTIC_GRADIENT_DESCENT"
        and not model._recurrent_names()
        and (guard is None or guard.policy != ROLLBACK)
        and all(
            getattr(l, "supports_batched_iterations", False)
            or hasattr(l, "chunk_done")
            for l in model.listeners
        )
    )


def run_megastep_chunk(model, stacked, *, step_fn=None, extra=None,
                       guard=None, on_restore=None, rows=None,
                       ls_active=None, sg_active=None) -> None:
    """One fused K-step megastep dispatch from pre-stacked device
    arrays ``(x, y, labels_mask, features_mask, k)``, followed by THE
    single per-chunk host readback (``megastep_readback``) and the
    host-side fan-out of what used to be per-step work: guard policy
    (from the read-back ok flags — consecutive-bad aborts fire at
    most K−1 steps late), listener callbacks (``chunk_done`` when the
    listener has one, else per-step ``iteration_done`` replayed from
    already-host scores at zero extra syncs), and one profiler
    record covering the chunk. ``step_fn``/``extra``/``guard``/
    ``on_restore``/``ls_active``/``sg_active`` let the distributed
    trainer substitute its sharded executable and its own guard's
    step flavor; the defaults serve the single-host engines."""
    from deeplearning4j_tpu.observability import profiler as _prof_mod

    xs, ys, masks, fmasks, k = stacked
    it0 = model.iteration_count
    prof = _prof_mod.get_active_profiler()
    if prof is not None:
        prof.begin_step(it0 + k)
    lr_stack, it0_dev = scan_consts(model, k, it0)
    if step_fn is None:
        if model._jit_megastep is None:
            model._jit_megastep = model._build_megastep()
        step_fn = model._jit_megastep
    if extra is None:
        extra = model._step_extra_args()
    out = step_fn(
        model.params, model.updater_state, model.state,
        xs, ys, masks, fmasks, lr_stack, it0_dev, model._base_key,
        *extra,
    )
    model.params, model.updater_state, model.state = out[:3]
    metrics, it0_next = out[3], out[4]
    i = 5
    if ls_active is None:
        ls_active = bool(getattr(model, "_loss_scale_active", False))
    if sg_active is None:
        sg_active = stat_guard_active(model)
    if ls_active:
        model._loss_scale_state = out[i]
        i += 1
    if sg_active:
        model._stat_guard_state = out[i]
    note_it0(model, it0_next, it0 + k)
    model.iteration_count += k
    note_megastep(k)
    host = megastep_readback(metrics)
    scores = host["scores"]
    model._last_score = float(scores[-1])
    if "grad_norms" in host:
        model._last_grad_norm = float(host["grad_norms"][-1])
    if guard is None:
        guard = getattr(model, "divergence_guard", None)
    if guard is not None and "oks" in host:
        # the in-jit select already suppressed each bad update, so
        # the trajectory needs nothing from the host — this only
        # applies the SKIP policy's ledger/abort bookkeeping, once
        # per chunk instead of once per step
        for j in range(k):
            if bool(host["oks"][j]):
                guard.good_step()
            else:
                guard.bad_step(model, on_restore=on_restore,
                               step_index=it0 + j + 1)
    if model.listeners:
        lt0 = time.perf_counter()
        for listener in model.listeners:
            cd = getattr(listener, "chunk_done", None)
            if cd is not None:
                cd(model, it0, k, host)
        per_step = [l for l in model.listeners
                    if not hasattr(l, "chunk_done")]
        if per_step:
            for j in range(k):
                model._last_score = float(scores[j])
                for listener in per_step:
                    listener.iteration_done(model, it0 + j + 1)
            model._last_score = float(scores[-1])
        if prof is not None:
            prof.note_listener_ms((time.perf_counter() - lt0) * 1e3)
    if prof is not None:
        prof.end_step(
            score=model._last_score,
            rows=rows if rows is not None else k * _chunk_rows(xs),
            chunk=k,
        )


def flush_megastep(model, batches: List[Any]) -> None:
    if len(batches) == 1:
        model.fit_minibatch(batches[0])
        return
    if _wants_last_features(model):
        model._last_features = batches[-1].features
    run_megastep_chunk(model, model._stack_chunk(batches))


def fit_epoch_megastep(model, it, prefetch=None) -> int:
    """Buffer same-shaped minibatches into blocks of
    ``model.megastep`` and run each block as one fused megastep
    dispatch. ``ChunkedDataSet``/``PlacedChunk`` items (pre-stacked
    [k, b, ...] payloads from a chunk-mode ``PrefetchIterator``) feed
    the dispatch directly — the double-buffered path where the next
    block's host->device copy overlaps the current dispatch. Partial
    or signature-changing tails fall back to the per-step program
    (same math — the mixed trajectory stays bitwise equal to the pure
    per-step loop). Chunk boundaries are the preemption/emergency
    checkpoint boundaries: an un-flushed buffer holds no dispatched
    work, so checkpoint staleness is bounded by K−1 steps."""
    from deeplearning4j_tpu.datasets.api import (
        ChunkedDataSet, PlacedChunk,
    )
    from deeplearning4j_tpu.parallel import control_plane
    from deeplearning4j_tpu.resilience import preemption

    model._reset_recurrent_state()
    k_target = int(model.megastep)
    buf: List[Any] = []
    sig = None
    n = 0
    for ds in it:
        preemption.check_fit(model, prefetch=prefetch)
        control_plane.check_fit(model)
        if isinstance(ds, (ChunkedDataSet, PlacedChunk)):
            if buf:
                flush_megastep(model, buf)
                buf, sig = [], None
            if ds.k >= 2:
                if _wants_last_features(model):
                    model._last_features = ds.features[-1]
                run_megastep_chunk(model, model._prep_prestacked(ds))
            else:
                for b in ds.to_datasets():
                    model.fit_minibatch(b)
            n += ds.k
            continue
        s = model._ds_scan_sig(ds)
        if buf and s != sig:
            flush_megastep(model, buf)
            buf = []
        sig = s
        buf.append(ds)
        n += 1
        if len(buf) >= k_target:
            flush_megastep(model, buf)
            buf = []
    if buf:
        flush_megastep(model, buf)
    return n


def fit_epochs_device_cached(model, iterator, epochs: int, arrays_of,
                             extra_plan_fn=None) -> bool:
    """Multi-epoch fit over a materialized dataset with the batches
    kept HBM-resident across epochs.

    The reference re-reads host data every epoch and re-copies it
    over PCIe (`MultipleEpochsIterator` + the per-op JNI hop,
    SURVEY.md §3.1); on TPU the host->device link is the scarce
    resource, so when the data is a fixed sequence that fits in
    device memory we transfer each fused chunk ONCE and re-run the
    scanned train step over the cached arrays every epoch. lr
    schedules/iteration counts are recomputed per chunk per epoch,
    so training semantics are identical to the streaming path.
    Returns False (caller streams as before) when ineligible."""
    plan = extra_plan_fn(iterator, epochs) if extra_plan_fn else None
    if plan is None:
        plan = cached_epoch_plan(model, iterator, epochs, arrays_of)
    if plan is None:
        return False
    for epoch in range(epochs):
        for listener in model.listeners:
            if hasattr(listener, "on_epoch_start"):
                listener.on_epoch_start(model)
        model._reset_recurrent_state()
        from deeplearning4j_tpu.parallel import control_plane
        from deeplearning4j_tpu.resilience import preemption

        for kind, item, last in plan:
            preemption.check_fit(model)
            control_plane.check_fit(model)
            if kind == "chunk":
                if _wants_last_features(model):
                    model._last_features = last.features
                run_scan_chunk(model, item)
            elif kind == "tbptt":
                if _wants_last_features(model):
                    model._last_features = last.features
                model._run_tbptt_stacked(item)
            else:
                model.fit_minibatch(item)
        for listener in model.listeners:
            if hasattr(listener, "on_epoch_end"):
                listener.on_epoch_end(model)
        model.epoch_count += 1
    return True


def fit_batches(model, iterator, epochs: int) -> None:
    """The epoch fit loop shared by both engines: optional pretrain,
    device-cached multi-epoch replay, scan-fused or per-step epochs
    through an ``AsyncDispatchWindow`` (bounded in-flight dispatch,
    guard flags collected late), epoch listener hooks, and iterator
    reset protocol."""
    if model.params is None:
        model.init()
    validator = getattr(model, "_batch_validator", None)
    if validator is not None:
        from deeplearning4j_tpu.datasets.validate import (
            ValidatingIterator,
        )

        if not isinstance(iterator, ValidatingIterator):
            # data-plane defense: rejects are quarantined before they
            # reach a step; the surviving stream is what trains
            iterator = ValidatingIterator(
                iterator, validator,
                quarantine=getattr(model, "_quarantine_store", None),
            )
    if model.conf.pretrain and not model._pretrain_done:
        # reference fit():1064 — layer-wise pretrain before backprop
        if not hasattr(iterator, "reset") and not isinstance(
            iterator, (list, tuple)
        ):
            iterator = list(iterator)
        model.pretrain(iterator)
    if not model.conf.backprop:
        return
    # megastep=K outranks the device-cached replay: the caller asked
    # for the fused-K executor (and its per-chunk readback contract)
    if not can_megastep(model) and model._fit_epochs_device_cached(
        iterator, epochs
    ):
        return
    from deeplearning4j_tpu.parallel import control_plane
    from deeplearning4j_tpu.parallel.dispatch import (
        AsyncDispatchWindow,
    )
    from deeplearning4j_tpu.resilience import preemption

    window = AsyncDispatchWindow(
        model=model,
        guard_fn=lambda: getattr(model, "divergence_guard", None),
        max_in_flight=model.max_in_flight,
        guard_lag=model.guard_lag,
    )
    try:
        for epoch in range(epochs):
            for listener in model.listeners:
                if hasattr(listener, "on_epoch_start"):
                    listener.on_epoch_start(model)
            it = iter(iterator)
            if can_megastep(model):
                n_batches = fit_epoch_megastep(
                    model, it,
                    prefetch=iterator
                    if hasattr(iterator, "shutdown") else None,
                )
            elif model._can_scan_steps() and model.scan_chunk > 1:
                n_batches = fit_epoch_scan(model, it)
            else:
                n_batches = 0
                model._dispatch_window = window
                try:
                    for ds in it:
                        # preemption notice -> drain + emergency
                        # checkpoint + PreemptedException (prefetch
                        # sources are shut down with a bounded join)
                        preemption.check_fit(
                            model, window=window,
                            prefetch=iterator
                            if hasattr(iterator, "shutdown") else None,
                        )
                        control_plane.check_fit(model)
                        model.fit_minibatch(ds)
                        n_batches += 1
                finally:
                    model._dispatch_window = None
                window.drain()  # guard aborts surface per epoch
            if epoch > 0 and n_batches == 0:
                raise ValueError(
                    "Iterator yielded no batches after the first "
                    "epoch — a plain generator cannot be "
                    "re-iterated; pass a list, a DataSetIterator "
                    "with reset(), or epochs=1"
                )
            if hasattr(iterator, "reset"):
                iterator.reset()
            for listener in model.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(model)
            model.epoch_count += 1
    except BaseException as e:
        window.abandon()  # keep the original exception
        from deeplearning4j_tpu.observability import flightrec
        from deeplearning4j_tpu.observability import (
            profiler as _prof_mod,
        )
        from deeplearning4j_tpu.resilience.preemption import (
            PreemptedException,
        )

        prof = _prof_mod.get_active_profiler()
        if prof is not None:
            prof.abandon_step()
        if not isinstance(e, PreemptedException):
            # preemption already attached the ring to the emergency
            # checkpoint manifest; everything else dumps to disk here
            flightrec.dump_on_crash("fit_exception")
        raise


# ---------------------------------------------------------------------------
# transform knob plumbing (shared by both engine wrappers)
# ---------------------------------------------------------------------------


def init_transforms(model, conf) -> None:
    """Seed the model's transform knobs from the (non-serialized)
    config hints and reset the derived caches. Called from both
    engines' constructors."""
    model.scan_layers = bool(getattr(conf, "scan_layers", False))
    model.remat = check_remat_policy(
        getattr(conf, "remat", None) or "none"
    )
    ls = getattr(conf, "loss_scale", None)
    model.loss_scale = (
        DEFAULT_LOSS_SCALE if ls is True else ls
    )
    model._layer_runs_cache = None
    model._loss_scale_state = None
    model._stat_guard_state = None
    model._batch_validator = None
    model._quarantine_store = None
    model.grad_accum = 1
    # K>1 folds K optimizer steps into one XLA dispatch (the
    # megastep executor); 1 = classic per-step dispatch
    model.megastep = int(getattr(conf, "megastep", 1) or 1)
    model._jit_megastep = None
    # {"shards": n} while the updater state lives in the zero
    # flattened-leaf layout (set/cleared by the distributed trainer's
    # placement); None = canonical per-param shapes
    model._zero_layout = None


def set_transforms(model, scan_layers=None, remat=None,
                   loss_scale=None, megastep=None) -> None:
    """Runtime (re)configuration of the whole-net transforms on either
    engine. ``None`` leaves a knob unchanged; changed knobs invalidate
    every compiled program that bakes them in. Transforms never change
    the math — trajectories are bitwise identical with them on or off
    (tier-1-asserted) — only the compiled program's shape (scan),
    memory plan (remat), or f16 gradient dynamic range (loss scale),
    or how many optimizer steps one dispatch covers (megastep)."""
    changed = False
    if megastep is not None:
        k = int(megastep)
        if k < 1:
            raise ValueError(f"megastep must be >= 1, got {megastep}")
        if k != int(getattr(model, "megastep", 1) or 1):
            model.megastep = k
            changed = True
    if scan_layers is not None and bool(scan_layers) != model.scan_layers:
        model.scan_layers = bool(scan_layers)
        model._layer_runs_cache = None
        changed = True
    if remat is not None and check_remat_policy(remat) != model.remat:
        model.remat = remat
        changed = True
    if loss_scale is not None:
        ls = DEFAULT_LOSS_SCALE if loss_scale is True else (
            loss_scale or None
        )
        if ls != model.loss_scale:
            model.loss_scale = ls
            model._loss_scale_state = None
            changed = True
    if changed:
        model._jit_step = None
        model._jit_multi_step = None
        model._jit_megastep = None
        model._jit_output = None
        model._jit_rnn_step = None
        if hasattr(model, "_jit_tbptt_multi_step"):
            model._jit_tbptt_multi_step = None


def set_batch_validator(model, validator, quarantine=None) -> None:
    """(Un)install the data-plane defense on a model's fit loops:
    ``fit_batches`` wraps its iterator in a ``ValidatingIterator``
    quarantining rejects to ``quarantine``. Host-side only — the
    compiled step is untouched."""
    model._batch_validator = validator
    model._quarantine_store = quarantine


def loss_scale_active(model) -> bool:
    """Dynamic loss scaling engages only for float16 compute (bf16
    shares f32's exponent range and needs none of it — unchanged)."""
    return (
        model.loss_scale is not None
        and compute_dtype_of(model.conf) == jnp.dtype(jnp.float16)
    )


def ensure_loss_scale_state(model):
    if model._loss_scale_state is None:
        model._loss_scale_state = loss_scale_state(model.loss_scale)
    return model._loss_scale_state


def stat_guard_active(model) -> bool:
    """The statistical anomaly guard engages when the installed
    divergence guard carries a ``StatGuardConfig``."""
    guard = getattr(model, "divergence_guard", None)
    return guard is not None and getattr(guard, "stats", None) is not None


def stat_guard_config(model):
    guard = getattr(model, "divergence_guard", None)
    return getattr(guard, "stats", None) if guard is not None else None


def ensure_stat_guard_state(model):
    """The model's device-resident EWMA state dict, created on first
    use (a checkpoint restore may have installed one already — the
    bitwise-resume path)."""
    if getattr(model, "_stat_guard_state", None) is None:
        from deeplearning4j_tpu.resilience.guard import stat_guard_state

        model._stat_guard_state = stat_guard_state()
    return model._stat_guard_state


def transform_kind_suffix(model) -> str:
    """AOT artifact-kind suffix for the transform knobs that change
    the compiled program (loss-scale changes the step's arity, scan/
    remat its HLO): part of the artifact identity so a stale
    executable is refused, not mis-dispatched."""
    parts = []
    if model.scan_layers:
        parts.append("scan")
    if model.remat != "none":
        parts.append(f"remat:{model.remat}")
    if getattr(model, "_loss_scale_active", False):
        parts.append("lossscale")
    if stat_guard_active(model):
        # a +statguard executable takes (and returns) the EWMA state;
        # refusing a stale plain artifact beats mis-dispatching it
        parts.append("statguard")
    if int(getattr(model, "grad_accum", 1)) > 1:
        parts.append(f"accum:{model.grad_accum}")
    if megastep_active(model):
        # a +mega:K executable is the K-step scanned program with a
        # different arity and return contract than the per-step one;
        # a stale artifact at any other K (or none) must be refused
        parts.append(f"mega:{model.megastep}")
    if getattr(model, "_zero_layout", None):
        # a +zero executable bakes in the flattened-leaf updater
        # layout; a stale plain-step artifact must be refused, not
        # fed flat state (and vice versa)
        parts.append("zero")
    kernels = kernel_kind_suffix(model)
    if kernels:
        # Pallas fused conv/dense kernels produce different HLO than
        # the plain XLA walk; an executable compiled with the kernels
        # off must be refused when dispatch is on (and vice versa).
        # "+tuned" extends the same refusal to the autotuner: measured
        # block configs change the kernels' tiling (and thus the HLO),
        # so an artifact compiled with tuning off must not install
        # while tuning is active (and vice versa).
        parts.extend(kernels.lstrip("+").split("+"))
    if has_row_sharded_embedding(model):
        # a +semb executable was traced with the embedding table's
        # rows sharded P("data", None); feeding it replicated params
        # (or vice versa) would silently recompile or mis-place — the
        # suffix forces the refusal path instead
        parts.append("semb")
    return ("+" + "+".join(parts)) if parts else ""


def kernel_kind_suffix(model) -> str:
    """The Pallas-kernel part of an AOT artifact kind, shared by the
    training-step suffix above and both engines' inference
    ``_output_kind``: ``+convblock`` when fused kernel dispatch is
    active, plus ``+tuned`` when the autotuner may swap in measured
    block configs (``DL4J_TPU_TUNE`` != off) — tuned tilings compile
    different HLO, so a mixed artifact must be refused, not
    mis-dispatched."""
    if not conv_block_dispatch_active(model):
        return ""
    from deeplearning4j_tpu.ops import autotune

    return "+convblock" + ("+tuned" if autotune.tuning_active() else "")


def _model_layer_confs(model):
    """Layer specs of either engine's config: the sequential list, or
    the layer-bearing vertices of a graph."""
    conf = model.conf
    layers = getattr(conf, "layers", None)
    if layers is not None:
        return list(layers)
    verts = getattr(conf, "vertices", None) or {}
    return [lc for lc in (v.layer() for v in verts.values())
            if lc is not None]


def has_row_sharded_embedding(model) -> bool:
    """True when either engine's config carries a
    ``SparseEmbeddingLayer`` with ``row_sharded=True`` — the marker
    the eligibility gates key on: ``DistributedTrainer`` shards that
    layer's ``W`` rows ``P("data", None)`` and must take the GSPMD
    step, ZeRO keeps the param replicated, and megastep refuses the
    model (see each gate's comment)."""
    from deeplearning4j_tpu.nn.layers.feedforward import (
        SparseEmbeddingLayer,
    )

    return any(
        isinstance(lc, SparseEmbeddingLayer)
        and getattr(lc, "row_sharded", False)
        for lc in _model_layer_confs(model)
    )


def conv_block_dispatch_active(model) -> bool:
    """True when Pallas fused-kernel dispatch is on AND the model has
    layers that route through it (conv/dense families). Deliberately
    coarse — a model whose only dense head is softmax over-refuses a
    stale artifact and falls back to JIT, which is safe; the converse
    (mis-dispatching an executable traced with different kernels)
    is not."""
    from deeplearning4j_tpu.ops.dispatch import use_pallas

    if not use_pallas():
        return False
    from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer

    return any(
        isinstance(lc, (ConvolutionLayer, DenseLayer))
        for lc in _model_layer_confs(model)
    )
