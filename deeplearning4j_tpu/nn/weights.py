"""Weight initialization (reference: ``nn/weights/WeightInit.java`` +
``WeightInitUtil.java``).

The reference computes fan-in/fan-out from the param shape and fills an
INDArray via nd4j RNG; here each scheme is a pure function of a jax PRNG
key, so initialization is reproducible from the config ``seed`` alone
and identical across hosts (important for multi-host init: every host
materializes identical params from the same key, no broadcast needed).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class Distribution:
    """Config bean for WeightInit.DISTRIBUTION (reference
    ``nn/conf/distribution/*.java``)."""

    kind: str = "normal"  # normal | uniform | binomial
    mean: float = 0.0
    std: float = 1.0
    lower: float = -1.0
    upper: float = 1.0
    n_trials: int = 1
    prob: float = 0.5

    def sample(self, key: jax.Array, shape: Sequence[int], dtype) -> jax.Array:
        if self.kind == "normal":
            return self.mean + self.std * jax.random.normal(key, shape, dtype)
        if self.kind == "uniform":
            return jax.random.uniform(
                key, shape, dtype, minval=self.lower, maxval=self.upper
            )
        if self.kind == "binomial":
            return jax.random.binomial(
                key, self.n_trials, self.prob, shape=shape, dtype=dtype
            )
        raise ValueError(f"Unknown distribution kind '{self.kind}'")

    def to_json(self) -> dict:
        return {
            "kind": self.kind, "mean": self.mean, "std": self.std,
            "lower": self.lower, "upper": self.upper,
            "n_trials": self.n_trials, "prob": self.prob,
        }

    @staticmethod
    def from_json(d: dict) -> "Distribution":
        return Distribution(**d)


def init_weights(
    key: jax.Array,
    shape: Sequence[int],
    weight_init: str,
    *,
    fan_in: float,
    fan_out: float,
    distribution: Distribution | None = None,
    dtype=jnp.float32,
) -> jax.Array:
    """Initialize a weight array per the named scheme.

    ``fan_in``/``fan_out`` are passed explicitly because for conv
    kernels they are receptive-field products, not raw dims (reference
    ``ConvolutionParamInitializer``).
    """
    shape = tuple(int(s) for s in shape)
    wi = weight_init.upper()
    if wi == "ZERO":
        return jnp.zeros(shape, dtype)
    if wi == "ONES":
        return jnp.ones(shape, dtype)
    if wi == "IDENTITY":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2-d shape")
        return jnp.eye(shape[0], dtype=dtype)
    if wi == "DISTRIBUTION":
        dist = distribution or Distribution()
        return dist.sample(key, shape, dtype)
    if wi == "NORMAL":  # N(0, 1/sqrt(fan_in)) — reference "NORMALIZED"-era
        return jax.random.normal(key, shape, dtype) / math.sqrt(max(fan_in, 1.0))
    if wi == "LECUN_NORMAL":
        return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / max(fan_in, 1.0))
    if wi == "XAVIER":
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return jax.random.normal(key, shape, dtype) * std
    if wi == "XAVIER_UNIFORM":
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if wi == "XAVIER_FAN_IN":
        return jax.random.normal(key, shape, dtype) / math.sqrt(max(fan_in, 1.0))
    if wi == "RELU":  # He init
        return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / max(fan_in, 1.0))
    if wi == "RELU_UNIFORM":
        a = math.sqrt(6.0 / max(fan_in, 1.0))
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if wi == "SIGMOID_UNIFORM":
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if wi == "UNIFORM":
        a = 1.0 / math.sqrt(max(fan_in, 1.0))
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if wi == "VI":  # legacy "variance init" from the reference era
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    raise ValueError(f"Unknown weight init '{weight_init}'")
