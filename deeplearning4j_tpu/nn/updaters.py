"""Per-layer optimizer machinery (reference: ``nn/updater/LayerUpdater``
+ nd4j ``GradientUpdater`` impls, ``LayerUpdater.java:243-266``, and
``MultiLayerUpdater`` aggregating per-layer state).

Design: the whole update is a pure function living *inside* the jitted
train step — gradient normalization, L1/L2 regularization, the updater
rule, and the parameter step fuse into one XLA program instead of the
reference's sequence of separate native op launches. Updater state is a
pytree shaped like the params pytree (the reference keeps one flat state
view array; a pytree is the idiomatic equivalent and shards the same
way params do under pjit).

Learning-rate policies (``LearningRatePolicy`` enum in the reference,
applied at ``LayerUpdater.applyLrDecayPolicy``) are computed host-side
per iteration and passed into the step as a traced scalar, so schedule
changes never trigger recompilation.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# Per-layer updater settings (extracted from layer configs by the network)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class UpdaterSettings:
    """Everything LayerUpdater needs for one layer."""

    updater: str = "SGD"
    learning_rate: float = 0.1
    bias_learning_rate: float | None = None
    bias_params: tuple = ("b",)
    momentum: float = 0.9  # NESTEROVS
    adam_mean_decay: float = 0.9
    adam_var_decay: float = 0.999
    rho: float = 0.95  # ADADELTA
    rms_decay: float = 0.95
    epsilon: float = 1e-8
    l1: float = 0.0
    l2: float = 0.0
    gradient_normalization: str = "None"
    gradient_normalization_threshold: float = 1.0
    # LR policy (host-side schedule)
    lr_policy: str = "None"
    lr_policy_decay_rate: float = 0.0
    lr_policy_steps: float = 1.0
    lr_policy_power: float = 1.0
    lr_score_decay: float = 0.0
    max_num_iterations: int = 100000
    lr_schedule: dict | None = None  # {iteration: lr}
    regularizable: tuple = ("W",)  # param names subject to l1/l2


def scheduled_lr(s: UpdaterSettings, iteration: int) -> float:
    """Host-side LR schedule (reference ``LearningRatePolicy``)."""
    lr = s.learning_rate
    p = s.lr_policy
    if p in ("None", "Score", None):
        return lr
    if p == "Exponential":
        return lr * (s.lr_policy_decay_rate ** iteration)
    if p == "Inverse":
        return lr / ((1.0 + s.lr_policy_decay_rate * iteration) ** s.lr_policy_power)
    if p == "Poly":
        frac = min(iteration / max(s.max_num_iterations, 1), 1.0)
        return lr * ((1.0 - frac) ** s.lr_policy_power)
    if p == "Sigmoid":
        return lr / (
            1.0 + math.exp(-s.lr_policy_decay_rate * (iteration - s.lr_policy_steps))
        )
    if p == "Step":
        return lr * (s.lr_policy_decay_rate ** math.floor(iteration / s.lr_policy_steps))
    if p == "TorchStep":
        # Reference persists each decay multiplicatively
        # (LayerUpdater.java:142): every iteration i in [2, iteration]
        # with steps % i == 0 compounds one decay factor.
        n_decays = sum(
            1 for i in range(2, iteration + 1)
            if s.lr_policy_steps % i == 0
        )
        return lr * (s.lr_policy_decay_rate ** n_decays)
    if p == "Schedule":
        if s.lr_schedule:
            best = None
            for k, v in s.lr_schedule.items():
                if int(k) <= iteration and (best is None or int(k) > best[0]):
                    best = (int(k), v)
            if best is not None:
                return best[1]
        return lr
    raise ValueError(f"Unknown LR policy '{p}'")


# ---------------------------------------------------------------------------
# Updater rules: state init + pure update
# ---------------------------------------------------------------------------


def _init_like(p, n):
    return tuple(jnp.zeros_like(p) for _ in range(n))


def init_param_state(s: UpdaterSettings, param: jax.Array) -> tuple:
    u = s.updater.upper()
    if u in ("SGD", "NONE"):
        return ()
    if u in ("NESTEROVS", "ADAGRAD", "RMSPROP"):
        return _init_like(param, 1)
    if u == "ADAM":
        return _init_like(param, 2)
    if u == "ADADELTA":
        return _init_like(param, 2)
    raise ValueError(f"Unknown updater '{s.updater}'")


def apply_updater(
    s: UpdaterSettings,
    grad: jax.Array,
    state: tuple,
    lr: jax.Array,
    t: jax.Array,
) -> tuple[jax.Array, tuple]:
    """Return (step, new_state); caller applies ``param -= step``.

    ``t`` is the 1-based iteration count (for Adam bias correction),
    traced so it never recompiles.
    """
    u = s.updater.upper()
    if u == "SGD":
        return lr * grad, ()
    if u == "NONE":
        return grad, ()
    if u == "NESTEROVS":
        (v,) = state
        v_new = s.momentum * v - lr * grad
        # reference Nesterovs: ret = -(mu * v_prev - (1 + mu) * v_new)
        step = s.momentum * v - (1.0 + s.momentum) * v_new
        return step, (v_new,)
    if u == "ADAGRAD":
        (h,) = state
        h_new = h + grad * grad
        return lr * grad / (jnp.sqrt(h_new) + s.epsilon), (h_new,)
    if u == "RMSPROP":
        (h,) = state
        h_new = s.rms_decay * h + (1.0 - s.rms_decay) * grad * grad
        return lr * grad / jnp.sqrt(h_new + s.epsilon), (h_new,)
    if u == "ADAM":
        m, v = state
        b1, b2 = s.adam_mean_decay, s.adam_var_decay
        m_new = b1 * m + (1.0 - b1) * grad
        v_new = b2 * v + (1.0 - b2) * grad * grad
        t_f = t.astype(m_new.dtype) if hasattr(t, "astype") else jnp.asarray(
            t, m_new.dtype
        )
        m_hat = m_new / (1.0 - b1 ** t_f)
        v_hat = v_new / (1.0 - b2 ** t_f)
        return lr * m_hat / (jnp.sqrt(v_hat) + s.epsilon), (m_new, v_new)
    if u == "ADADELTA":
        eg, ex = state
        rho = s.rho
        eg_new = rho * eg + (1.0 - rho) * grad * grad
        dx = grad * jnp.sqrt(ex + s.epsilon) / jnp.sqrt(eg_new + s.epsilon)
        ex_new = rho * ex + (1.0 - rho) * dx * dx
        return dx, (eg_new, ex_new)
    raise ValueError(f"Unknown updater '{s.updater}'")


# ---------------------------------------------------------------------------
# Gradient normalization (reference GradientNormalization enum,
# applied in LayerUpdater.preApply)
# ---------------------------------------------------------------------------


def normalize_layer_grads(
    s: UpdaterSettings, grads: dict[str, jax.Array]
) -> dict[str, jax.Array]:
    gn = s.gradient_normalization
    if gn in ("None", None):
        return grads
    thr = s.gradient_normalization_threshold
    if gn == "RenormalizeL2PerLayer":
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in grads.values()) + 1e-12)
        return {k: g / norm for k, g in grads.items()}
    if gn == "RenormalizeL2PerParamType":
        return {
            k: g / jnp.sqrt(jnp.sum(g * g) + 1e-12) for k, g in grads.items()
        }
    if gn == "ClipElementWiseAbsoluteValue":
        return {k: jnp.clip(g, -thr, thr) for k, g in grads.items()}
    if gn == "ClipL2PerLayer":
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in grads.values()) + 1e-12)
        scale = jnp.minimum(1.0, thr / norm)
        return {k: g * scale for k, g in grads.items()}
    if gn == "ClipL2PerParamType":
        out = {}
        for k, g in grads.items():
            norm = jnp.sqrt(jnp.sum(g * g) + 1e-12)
            out[k] = g * jnp.minimum(1.0, thr / norm)
        return out
    raise ValueError(f"Unknown gradient normalization '{gn}'")


# ---------------------------------------------------------------------------
# Multi-layer aggregation (reference MultiLayerUpdater)
# ---------------------------------------------------------------------------


class MultiLayerUpdaterDef:
    """Holds per-layer UpdaterSettings; provides pure init/update over
    the whole network's params pytree ``{layer_name: {param: array}}``."""

    def __init__(self, settings: dict[str, UpdaterSettings]):
        self.settings = settings

    def init(self, params: dict[str, dict[str, jax.Array]]):
        return {
            ln: {
                pn: init_param_state(self.settings[ln], p)
                for pn, p in lp.items()
            }
            for ln, lp in params.items()
        }

    def scheduled_lrs(self, iteration: int) -> dict[str, float]:
        return {
            ln: scheduled_lr(s, iteration) for ln, s in self.settings.items()
        }

    def update(
        self,
        grads: dict[str, dict[str, jax.Array]],
        state: dict,
        params: dict[str, dict[str, jax.Array]],
        lrs: dict[str, jax.Array],
        t: jax.Array,
        flatten=None,
        unflatten=None,
    ):
        """Pure: returns (new_params, new_state). Runs inside jit.

        L1/L2 regularization is NOT added here: the penalty lives in
        the network's score function, so ``jax.grad`` already includes
        ``l2*W + l1*sign(W)`` exactly once (the reference adds it in
        ``postApply`` because its loss gradient excludes the penalty;
        adding it here too would double-apply it). Consequence vs the
        reference: gradient normalization acts on the penalty-inclusive
        gradient.

        Biases (param names in ``s.bias_params``) use
        ``bias_learning_rate`` when configured (reference
        ``biasLearningRate``).

        ``flatten``/``unflatten`` select the ZeRO flattened-leaf
        layout (nn/core.py): ``state`` leaves are 1-d zero-padded
        vectors, gradients are flattened before the rule and the
        stepped params restored to their canonical shapes after. The
        rules are elementwise, so the flat math is bitwise the
        canonical math; padding slots carry grad 0 / state 0, which
        every rule maps back to step 0 / state 0. Gradient
        normalization runs BEFORE the flatten, on the full-shape
        gradients, so per-layer norms are unchanged.
        """
        new_params: dict[str, Any] = {}
        new_state: dict[str, Any] = {}
        for ln, lgrads in grads.items():
            s = self.settings[ln]
            lgrads = normalize_layer_grads(s, lgrads)
            lr = lrs[ln]
            bias_scale = (
                s.bias_learning_rate / s.learning_rate
                if (s.bias_learning_rate is not None and s.learning_rate != 0)
                else 1.0
            )
            np_, ns_ = {}, {}
            for pn, g in lgrads.items():
                p = params[ln][pn]
                p_lr = lr * bias_scale if pn in s.bias_params else lr
                if flatten is not None:
                    step, st = apply_updater(
                        s, flatten(g), state[ln][pn], p_lr, t
                    )
                    stepped = (flatten(p) - step).astype(p.dtype)
                    np_[pn] = unflatten(stepped, p.shape)
                else:
                    step, st = apply_updater(
                        s, g, state[ln][pn], p_lr, t
                    )
                    np_[pn] = (p - step).astype(p.dtype)
                # keep param AND state dtypes: the f32 lr would promote
                # bf16 params/momenta (and break the scan path's fixed
                # carry dtype)
                ns_[pn] = tuple(
                    a.astype(o.dtype)
                    for a, o in zip(st, state[ln][pn])
                )
            new_params[ln] = np_
            new_state[ln] = ns_
        return new_params, new_state
