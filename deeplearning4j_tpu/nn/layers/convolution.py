"""Convolutional stack (reference: ``nn/layers/convolution/
ConvolutionLayer.java`` im2col+gemm path, ``SubsamplingLayer.java``,
and the whole ``deeplearning4j-cuda`` module's four cuDNN helpers —
``CudnnConvolutionHelper``, ``CudnnSubsamplingHelper``,
``CudnnBatchNormalizationHelper``, ``CudnnLocalResponseNormalizationHelper``).

TPU-first design: the reference needs im2col+gemm OR a cuDNN helper
per layer because it schedules ops by hand; on TPU a single
``lax.conv_general_dilated`` lowers straight to MXU convolutions and
XLA fuses bias+activation into it, so the helper-vs-builtin split
(and the ``AlgoMode`` autotune knob) dissolves — XLA autotunes tile
shapes itself. Pooling is ``lax.reduce_window``; batch-norm is inlined
arithmetic XLA fuses with the surrounding conv.

Data layout is NCHW at the API (reference parity); weights are OIHW
``[nOut, nIn, kh, kw]`` matching the reference's param shape so
checkpoints map 1:1.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import LayerSpec, register_layer
from deeplearning4j_tpu.nn.weights import init_weights


def _pair(v) -> tuple:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _out_size(size: int, k: int, s: int, p: int) -> int:
    """Reference ``KernelValidationUtil`` output-shape math."""
    out = (size + 2 * p - k) // s + 1
    if out <= 0:
        raise ValueError(
            f"Invalid conv/pool geometry: input {size}, kernel {k}, "
            f"stride {s}, padding {p} -> output {out}"
        )
    return out


@register_layer
@dataclass(frozen=True)
class ConvolutionLayer(LayerSpec):
    """2-D convolution (reference ``nn/conf/layers/ConvolutionLayer`` +
    impl). ``algo_mode`` is accepted for config parity but is a no-op:
    XLA autotunes (reference uses it to pick cuDNN algorithms)."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: tuple = (5, 5)
    stride: tuple = (1, 1)
    padding: tuple = (0, 0)
    algo_mode: str = "PREFER_FASTEST"
    activation: str = "identity"
    weight_init: str = "XAVIER"

    def input_kind(self) -> str:
        return "convolutional"

    def with_input_type(self, it: InputType) -> "ConvolutionLayer":
        if self.n_in == 0 and it.kind in ("convolutional", "convolutionalFlat"):
            return dataclasses.replace(self, n_in=it.channels)
        return self

    def output_type(self, it: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        return InputType.convolutional(
            _out_size(it.height, kh, sh, ph),
            _out_size(it.width, kw, sw, pw),
            self.n_out,
        )

    def init_params(self, key, dtype=jnp.float32) -> dict:
        kh, kw = _pair(self.kernel_size)
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        w = init_weights(
            key, (self.n_out, self.n_in, kh, kw), self.weight_init,
            fan_in=fan_in, fan_out=fan_out, distribution=self.dist,
            dtype=dtype,
        )
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": w, "b": b}

    def pre_output(self, params, x):
        from deeplearning4j_tpu.ops.dispatch import effective_platform

        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        if effective_platform() == "tpu":
            # TPU: XLA relayouts freely; NCHW and NHWC compile to the
            # same MXU convolutions (measured equal)
            y = lax.conv_general_dilated(
                x, params["W"],
                window_strides=(sh, sw),
                padding=((ph, ph), (pw, pw)),
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
            )
        else:
            # CPU: XLA's fast (Eigen) conv kernels exist ONLY for
            # NHWC — the NCHW lowering is a naive loop, measured 38x
            # slower at ResNet shapes. The API stays NCHW (reference
            # parity); the transposes fuse into the surrounding ops.
            y = lax.conv_general_dilated(
                jnp.transpose(x, (0, 2, 3, 1)),
                jnp.transpose(params["W"], (2, 3, 1, 0)),  # OIHW->HWIO
                window_strides=(sh, sw),
                padding=((ph, ph), (pw, pw)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y + params["b"].reshape(1, -1, 1, 1)

    def supports_drop_connect(self) -> bool:
        return True

    def _kernel_eligible(self, params, x, activation: str) -> bool:
        """Whether the fused Pallas conv kernel can take this apply
        call: supported epilogue and a VMEM-fitting tiling (see
        ``ops.conv_block.conv_block_ok``)."""
        from deeplearning4j_tpu.ops import SUPPORTED_EPILOGUES, conv_block_ok

        return (
            x.ndim == 4
            and activation in SUPPORTED_EPILOGUES
            and conv_block_ok(
                x.shape, params["W"].shape, _pair(self.stride),
                _pair(self.padding), x.dtype,
            )
        )

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        params = self.maybe_drop_connect(params, train=train, rng=rng)
        from deeplearning4j_tpu.ops import conv_block, dispatch

        act = self.activation.lower()
        if dispatch.route("conv_block",
                          self._kernel_eligible(params, x, act)):
            y = conv_block(
                x, params["W"], params["b"],
                stride=_pair(self.stride), padding=_pair(self.padding),
                activation=act,
            )
            return y, state
        return self.activate_fn()(self.pre_output(params, x)), state


@register_layer
@dataclass(frozen=True)
class SubsamplingLayer(LayerSpec):
    """Spatial pooling: MAX / AVG / SUM (reference
    ``nn/conf/layers/SubsamplingLayer`` ``PoolingType`` +
    ``CudnnSubsamplingHelper``) via ``lax.reduce_window``."""

    pooling_type: str = "MAX"
    kernel_size: tuple = (2, 2)
    stride: tuple = (2, 2)
    padding: tuple = (0, 0)
    activation: str = "identity"

    def input_kind(self) -> str:
        return "convolutional"

    def output_type(self, it: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        return InputType.convolutional(
            _out_size(it.height, kh, sh, ph),
            _out_size(it.width, kw, sw, pw),
            it.channels,
        )

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dims = (1, 1, kh, kw)
        strides = (1, 1, sh, sw)
        pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
        pt = self.pooling_type.upper()
        if pt == "MAX":
            init = -jnp.inf
            y = lax.reduce_window(x, init, lax.max, dims, strides, pads)
        elif pt in ("AVG", "SUM"):
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
            if pt == "AVG":
                y = y / (kh * kw)
        else:
            raise ValueError(f"Unknown pooling type '{self.pooling_type}'")
        return y, state


@register_layer
@dataclass(frozen=True)
class BatchNormalization(LayerSpec):
    """Batch normalization (reference ``nn/layers/normalization/
    BatchNormalization.java`` + ``CudnnBatchNormalizationHelper``).

    Works on CNN [b,c,h,w] (per-channel) and FF [b,n] (per-feature)
    activations like the reference. Running mean/var live in the layer
    *state* pytree and are updated functionally inside the jitted step
    (the reference mutates INDArray fields)."""

    n_out: int = 0
    decay: float = 0.9
    eps: float = 1e-5
    gamma_init: float = 1.0
    beta_init: float = 0.0
    lock_gamma_beta: bool = False
    activation: str = "identity"

    def input_kind(self) -> str:
        return "any"

    def with_input_type(self, it: InputType) -> "BatchNormalization":
        if self.n_out == 0:
            n = it.channels if it.kind == "convolutional" else it.flat_size()
            return dataclasses.replace(self, n_out=n)
        return self

    def output_type(self, it: InputType) -> InputType:
        return it

    def regularizable_params(self) -> tuple:
        return ()  # reference: gamma/beta not regularized

    def uses_batch_statistics(self) -> bool:
        return True

    def init_params(self, key, dtype=jnp.float32) -> dict:
        if self.lock_gamma_beta:
            return {}
        return {
            "gamma": jnp.full((self.n_out,), self.gamma_init, dtype),
            "beta": jnp.full((self.n_out,), self.beta_init, dtype),
        }

    def init_state(self, dtype=jnp.float32) -> dict:
        return {
            "mean": jnp.zeros((self.n_out,), dtype),
            "var": jnp.ones((self.n_out,), dtype),
        }

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        if x.ndim == 4:
            axes = (0, 2, 3)
            bshape = (1, -1, 1, 1)
        else:
            axes = (0,)
            bshape = (1, -1)
        if train:
            cnt = float(np.prod([x.shape[a] for a in axes]))
            if x.dtype in (jnp.bfloat16, jnp.float16):
                # ONE pass over x: sum and sum-of-squares are
                # independent reductions XLA multi-output-fuses into a
                # single read (jnp.mean-then-jnp.var chains the passes
                # — var's input depends on mean — costing an extra
                # full read of the [b,c,h,w] activation per BN layer;
                # measured on the ResNet-50 trace as part of the 34%
                # loop-fusion share). E[x^2]-E[x]^2 cancels only when
                # mean^2/var >> 2^24 in the f32 accumulator — far
                # beyond anything a half-precision activation can
                # even represent distinctly, so the one-pass form is
                # reserved for the low-precision compute dtypes where
                # the bandwidth matters and the cancellation cannot.
                xf = x.astype(jnp.float32)
                s1 = jnp.sum(xf, axis=axes)
                s2 = jnp.sum(xf * xf, axis=axes)
                mean = s1 / cnt
                var = jnp.maximum(s2 / cnt - mean * mean, 0.0)
            else:
                # f32/f64: numerically safe two-pass centered variance
                mean = jnp.mean(x, axis=axes)
                var = jnp.mean(
                    jnp.square(x - mean.reshape(bshape)), axis=axes
                )
            new_state = {
                "mean": (self.decay * state["mean"]
                         + (1 - self.decay) * mean.astype(state["mean"].dtype)),
                "var": (self.decay * state["var"]
                        + (1 - self.decay) * var.astype(state["var"].dtype)),
            }
        else:
            # running stats live in master precision; normalize in the
            # activation dtype so mixed-precision inference stays in
            # the compute dtype instead of promoting downstream to f32
            acc_dt = jnp.promote_types(x.dtype, jnp.float32)
            mean = state["mean"].astype(acc_dt)
            var = state["var"].astype(acc_dt)
            new_state = state
        # fold to a per-channel affine (y = a*x + b): the apply pass
        # is then a single fused elementwise read-modify-write, and
        # the [C]-sized coefficient math stays off the hot pass
        a, b = self._affine_from_stats(params, mean, var)
        y = x * a.astype(x.dtype).reshape(bshape) + \
            b.astype(x.dtype).reshape(bshape)
        return self.activate_fn()(y), new_state

    def _affine_from_stats(self, params, mean, var):
        inv = lax.rsqrt(var + self.eps)
        if self.lock_gamma_beta:
            return inv, -mean * inv
        a = params["gamma"].astype(inv.dtype) * inv
        b = params["beta"].astype(inv.dtype) - mean * a
        return a, b

    def folded_affine(self, params, state):
        """The eval-mode normalization folded to per-channel ``(a, b)``
        with ``y = a*x + b`` — the same coefficients the eval branch of
        ``apply`` uses, exposed so the conv->BN inference peephole can
        hand them to the fused conv kernel's epilogue."""
        acc_dt = jnp.promote_types(state["mean"].dtype, jnp.float32)
        return self._affine_from_stats(
            params, state["mean"].astype(acc_dt),
            state["var"].astype(acc_dt),
        )


@register_layer
@dataclass(frozen=True)
class LocalResponseNormalization(LayerSpec):
    """Cross-channel LRN (reference ``nn/layers/normalization/
    LocalResponseNormalization.java`` +
    ``CudnnLocalResponseNormalizationHelper``), Krizhevsky form as in
    the reference's builtin path: y = x / (k + alpha * sum_{j in
    window} x_j^2)^beta."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75
    activation: str = "identity"

    def input_kind(self) -> str:
        return "convolutional"

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        half = self.n // 2
        sq = x * x
        # windowed sum over the channel axis via reduce_window;
        # asymmetric padding keeps the channel count for even n
        summed = lax.reduce_window(
            sq, 0.0, lax.add,
            window_dimensions=(1, self.n, 1, 1),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (half, self.n - 1 - half), (0, 0), (0, 0)),
        )
        denom = (self.k + self.alpha * summed) ** self.beta
        return x / denom, state


def maybe_fused_conv_bn(conv, bn, conv_params, bn_params, bn_state, x):
    """Inference peephole: Conv(identity) -> BatchNormalization(act)
    collapsed into ONE fused kernel call — the BN running stats fold to
    a per-channel affine (``folded_affine``) that rides the conv
    kernel's epilogue, deleting the separate normalize+activate HBM
    round-trip. Returns the fused activation, or None when the fused
    path does not engage (wrong layer pair, unsupported epilogue,
    no VMEM-fitting tiling, or Pallas dispatch off) — the caller then
    falls back to the ordinary layer-by-layer walk, which keeps
    kernel-off trajectories bitwise untouched. Training never fuses:
    batch stats depend on the conv output itself."""
    if not (isinstance(conv, ConvolutionLayer)
            and isinstance(bn, BatchNormalization)
            and conv.activation.lower() == "identity"
            and x.ndim == 4
            and bn.n_out == conv.n_out
            and bn_state):
        return None
    from deeplearning4j_tpu.ops import conv_block, dispatch

    act = bn.activation.lower()
    if not (conv._kernel_eligible(conv_params, x, act)
            and dispatch.use_pallas()):
        # no metric here: the unfused walk's own conv_block route
        # records the decision for this conv
        return None
    dispatch.note_dispatch(
        "conv_bn_block",
        "interpret" if dispatch.pallas_interpret() else "pallas",
    )
    a, b = bn.folded_affine(bn_params, bn_state)
    return conv_block(
        x, conv_params["W"], conv_params["b"], a, b,
        stride=_pair(conv.stride), padding=_pair(conv.padding),
        activation=act,
    )
