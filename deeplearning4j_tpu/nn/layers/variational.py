"""Variational autoencoder layer + reconstruction distributions
(reference: ``nn/layers/variational/VariationalAutoencoder.java:43``
and ``nn/conf/layers/variational/*.java``).

The reference implements the VAE as a pretrain-only layer with
hand-written forward/backward over encoder/decoder sub-stacks and a
``ReconstructionDistribution`` SPI (Bernoulli, Gaussian, Exponential,
Composite, LossFunctionWrapper). Here the whole ELBO —
encoder, reparameterization sample, decoder, reconstruction
log-likelihood, KL(q(z|x) || N(0,I)) — is one pure traced function;
``jax.grad`` replaces the reference's manual backprop through both
sub-stacks, and XLA fuses the MC-sample loop (vmapped, not a Python
loop) into a batched matmul program for the MXU.

When used inside a supervised net, ``apply`` outputs the activated
mean of q(z|x) (reference ``activate()`` returns pzxMean-based
activations).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn import losses as losses_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import (
    FeedForwardLayerSpec,
    register_layer,
)
from deeplearning4j_tpu.nn.weights import init_weights

# ---------------------------------------------------------------------------
# Reconstruction distributions (reference nn/conf/layers/variational/
# ReconstructionDistribution.java SPI: distributionInputSize,
# negLogProbability, generateAtMean/generateRandom)
# ---------------------------------------------------------------------------

_DISTRIBUTION_REGISTRY: dict = {}


def register_distribution(cls):
    _DISTRIBUTION_REGISTRY[cls.__name__] = cls
    return cls


@dataclass(frozen=True)
class ReconstructionDistribution:
    """SPI for p(x|z) families."""

    activation: str = "identity"

    def param_size(self, data_size: int) -> int:
        """Number of decoder outputs needed per data dim (reference
        ``distributionInputSize``)."""
        raise NotImplementedError

    def neg_log_prob(self, x, preout) -> jax.Array:
        """Per-example -log p(x|z): [batch] from x [batch, d] and raw
        decoder preoutput [batch, param_size(d)]."""
        raise NotImplementedError

    def generate_at_mean(self, preout) -> jax.Array:
        raise NotImplementedError

    def generate_random(self, rng, preout) -> jax.Array:
        raise NotImplementedError

    # serde -----------------------------------------------------------------

    def to_json(self) -> dict:
        d = {"@dist_class": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if f.name == "components":
                v = [[s, c.to_json()] for s, c in v]
            d[f.name] = v
        return d

    @staticmethod
    def from_json(d: dict) -> "ReconstructionDistribution":
        d = dict(d)
        cls = _DISTRIBUTION_REGISTRY[d.pop("@dist_class")]
        if cls is CompositeReconstructionDistribution:
            comps = tuple(
                (int(s), ReconstructionDistribution.from_json(c))
                for s, c in d.get("components", [])
            )
            return cls(components=comps)
        return cls(**d)


@register_distribution
@dataclass(frozen=True)
class BernoulliReconstructionDistribution(ReconstructionDistribution):
    """p(x|z) = Bernoulli(sigmoid(preout)) (reference
    ``BernoulliReconstructionDistribution.java``)."""

    activation: str = "sigmoid"

    def param_size(self, data_size: int) -> int:
        return data_size

    def neg_log_prob(self, x, preout) -> jax.Array:
        if self.activation == "sigmoid":
            # numerically stable sigmoid cross-entropy on logits
            nll = jnp.maximum(preout, 0) - preout * x + jnp.log1p(
                jnp.exp(-jnp.abs(preout))
            )
        else:
            p = jnp.clip(activations.get(self.activation)(preout), 1e-7, 1 - 1e-7)
            nll = -(x * jnp.log(p) + (1 - x) * jnp.log1p(-p))
        return jnp.sum(nll, axis=-1)

    def generate_at_mean(self, preout) -> jax.Array:
        return activations.get(self.activation)(preout)

    def generate_random(self, rng, preout) -> jax.Array:
        p = activations.get(self.activation)(preout)
        return jax.random.bernoulli(rng, p).astype(preout.dtype)


@register_distribution
@dataclass(frozen=True)
class GaussianReconstructionDistribution(ReconstructionDistribution):
    """p(x|z) = N(mean, diag(sigma^2)); decoder outputs
    [mean, log(sigma^2)] concatenated (reference
    ``GaussianReconstructionDistribution.java``)."""

    def param_size(self, data_size: int) -> int:
        return 2 * data_size

    def _split(self, preout):
        d = preout.shape[-1] // 2
        act = activations.get(self.activation)
        return act(preout[..., :d]), preout[..., d:]

    def neg_log_prob(self, x, preout) -> jax.Array:
        mean, log_var = self._split(preout)
        log_var = jnp.clip(log_var, -10.0, 10.0)
        nll = 0.5 * (
            jnp.log(2 * jnp.pi) + log_var
            + (x - mean) ** 2 / jnp.exp(log_var)
        )
        return jnp.sum(nll, axis=-1)

    def generate_at_mean(self, preout) -> jax.Array:
        mean, _ = self._split(preout)
        return mean

    def generate_random(self, rng, preout) -> jax.Array:
        mean, log_var = self._split(preout)
        std = jnp.exp(0.5 * jnp.clip(log_var, -10.0, 10.0))
        return mean + std * jax.random.normal(rng, mean.shape, mean.dtype)


@register_distribution
@dataclass(frozen=True)
class ExponentialReconstructionDistribution(ReconstructionDistribution):
    """p(x|z) = Exp(lambda), lambda = exp(act(preout)) (reference
    ``ExponentialReconstructionDistribution.java``: gamma = act(preout),
    lambda = exp(gamma); -log p = lambda*x - gamma)."""

    def param_size(self, data_size: int) -> int:
        return data_size

    def neg_log_prob(self, x, preout) -> jax.Array:
        gamma = activations.get(self.activation)(preout)
        gamma = jnp.clip(gamma, -20.0, 20.0)
        lam = jnp.exp(gamma)
        return jnp.sum(lam * x - gamma, axis=-1)

    def generate_at_mean(self, preout) -> jax.Array:
        gamma = jnp.clip(activations.get(self.activation)(preout), -20.0, 20.0)
        return jnp.exp(-gamma)  # mean = 1/lambda

    def generate_random(self, rng, preout) -> jax.Array:
        gamma = jnp.clip(activations.get(self.activation)(preout), -20.0, 20.0)
        u = jax.random.uniform(
            rng, preout.shape, preout.dtype, minval=1e-7, maxval=1.0
        )
        return -jnp.log(u) * jnp.exp(-gamma)


@register_distribution
@dataclass(frozen=True)
class LossFunctionWrapper(ReconstructionDistribution):
    """Plain loss function as a pseudo reconstruction distribution
    (reference ``LossFunctionWrapper.java``); makes the VAE a
    regularized autoencoder."""

    loss: str = "MSE"

    def param_size(self, data_size: int) -> int:
        return data_size

    def neg_log_prob(self, x, preout) -> jax.Array:
        return losses_mod.per_row_scores(self.loss, x, preout, self.activation)

    def generate_at_mean(self, preout) -> jax.Array:
        return activations.get(self.activation)(preout)

    def generate_random(self, rng, preout) -> jax.Array:
        return self.generate_at_mean(preout)


@register_distribution
@dataclass(frozen=True)
class CompositeReconstructionDistribution(ReconstructionDistribution):
    """Different distributions over slices of the data vector
    (reference ``CompositeReconstructionDistribution.java``).
    ``components``: tuple of (data_size, distribution)."""

    components: Tuple[Tuple[int, ReconstructionDistribution], ...] = ()

    def param_size(self, data_size: int) -> int:
        total_data = sum(s for s, _ in self.components)
        if total_data != data_size:
            raise ValueError(
                f"Composite component sizes sum to {total_data}, "
                f"but data size is {data_size}"
            )
        return sum(d.param_size(s) for s, d in self.components)

    def _slices(self):
        xo = po = 0
        for s, d in self.components:
            ps = d.param_size(s)
            yield xo, s, po, ps, d
            xo += s
            po += ps

    def neg_log_prob(self, x, preout) -> jax.Array:
        total = 0.0
        for xo, s, po, ps, d in self._slices():
            total = total + d.neg_log_prob(
                x[..., xo:xo + s], preout[..., po:po + ps]
            )
        return total

    def generate_at_mean(self, preout) -> jax.Array:
        outs = [
            d.generate_at_mean(preout[..., po:po + ps])
            for _, _, po, ps, d in self._slices()
        ]
        return jnp.concatenate(outs, axis=-1)

    def generate_random(self, rng, preout) -> jax.Array:
        outs = []
        for i, (_, _, po, ps, d) in enumerate(self._slices()):
            outs.append(
                d.generate_random(
                    jax.random.fold_in(rng, i), preout[..., po:po + ps]
                )
            )
        return jnp.concatenate(outs, axis=-1)


# ---------------------------------------------------------------------------
# The VAE layer
# ---------------------------------------------------------------------------


@register_layer
@dataclass(frozen=True)
class VariationalAutoencoder(FeedForwardLayerSpec):
    """Variational autoencoder (reference
    ``nn/conf/layers/variational/VariationalAutoencoder.java`` +
    ``nn/layers/variational/VariationalAutoencoder.java``).

    ``n_out`` is the latent size. Param names mirror the reference's
    (``VariationalAutoencoderParamInitializer``): eW{i}/eb{i} encoder,
    pZXMeanW/b + pZXLogStd2W/b posterior heads, dW{i}/db{i} decoder,
    pXZW/b reconstruction head.
    """

    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    pzx_activation: str = "identity"
    reconstruction_distribution: ReconstructionDistribution = (
        BernoulliReconstructionDistribution()
    )
    num_samples: int = 1

    def is_pretrainable(self) -> bool:
        return True

    def regularizable_params(self) -> tuple:
        names = ["pZXMeanW", "pZXLogStd2W", "pXZW"]
        names += [f"eW{i}" for i in range(len(self.encoder_layer_sizes))]
        names += [f"dW{i}" for i in range(len(self.decoder_layer_sizes))]
        return tuple(names)

    # -- params -------------------------------------------------------------

    def init_params(self, key, dtype=jnp.float32) -> dict:
        recon_size = self.reconstruction_distribution.param_size(self.n_in)
        shapes = []
        prev = self.n_in
        for i, h in enumerate(self.encoder_layer_sizes):
            shapes.append((f"eW{i}", f"eb{i}", prev, h))
            prev = h
        shapes.append(("pZXMeanW", "pZXMeanb", prev, self.n_out))
        shapes.append(("pZXLogStd2W", "pZXLogStd2b", prev, self.n_out))
        prev = self.n_out
        for i, h in enumerate(self.decoder_layer_sizes):
            shapes.append((f"dW{i}", f"db{i}", prev, h))
            prev = h
        shapes.append(("pXZW", "pXZb", prev, recon_size))
        params = {}
        keys = jax.random.split(key, len(shapes))
        for k, (wn, bn, fi, fo) in zip(keys, shapes):
            params[wn] = init_weights(
                k, (fi, fo), self.weight_init, fan_in=fi, fan_out=fo,
                distribution=self.dist, dtype=dtype,
            )
            params[bn] = jnp.full((fo,), self.bias_init, dtype)
        return params

    # -- sub-stacks ---------------------------------------------------------

    def _encode(self, params, x):
        act = self.activate_fn()
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = act(h @ params[f"eW{i}"] + params[f"eb{i}"])
        pzx_act = activations.get(self.pzx_activation)
        mean = pzx_act(h @ params["pZXMeanW"] + params["pZXMeanb"])
        log_var = h @ params["pZXLogStd2W"] + params["pZXLogStd2b"]
        return mean, jnp.clip(log_var, -10.0, 10.0)

    def _decode(self, params, z):
        act = self.activate_fn()
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = act(h @ params[f"dW{i}"] + params[f"db{i}"])
        return h @ params["pXZW"] + params["pXZb"]  # raw distribution params

    # -- supervised forward: activated posterior mean -----------------------

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        mean, _ = self._encode(params, x)
        return mean, state

    # -- ELBO pretraining ---------------------------------------------------

    def pretrain_loss(self, params, x, rng):
        """Mean negative ELBO over the batch: E_q[-log p(x|z)] (MC with
        ``num_samples``) + KL(q(z|x) || N(0, I))."""
        mean, log_var = self._encode(params, x)
        kl = 0.5 * jnp.sum(
            jnp.exp(log_var) + mean**2 - 1.0 - log_var, axis=-1
        )
        dist = self.reconstruction_distribution

        if rng is None:
            recon = dist.neg_log_prob(x, self._decode(params, mean))
        else:
            def sample_nll(k):
                eps = jax.random.normal(k, mean.shape, mean.dtype)
                z = mean + jnp.exp(0.5 * log_var) * eps
                return dist.neg_log_prob(x, self._decode(params, z))

            keys = jax.random.split(rng, self.num_samples)
            recon = jnp.mean(jax.vmap(sample_nll)(keys), axis=0)
        return jnp.mean(recon + kl)

    # -- generation / scoring (reference generateAtMeanGivenZ etc.) ---------

    def reconstruction_probability(self, params, x, rng, num_samples=None):
        """Per-example -log p(x) estimate (reference
        ``reconstructionLogProbability`` sign-flipped): MC-averaged
        reconstruction nll + KL."""
        n = num_samples or self.num_samples
        mean, log_var = self._encode(params, x)
        kl = 0.5 * jnp.sum(
            jnp.exp(log_var) + mean**2 - 1.0 - log_var, axis=-1
        )

        def sample_nll(k):
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * log_var) * eps
            return self.reconstruction_distribution.neg_log_prob(
                x, self._decode(params, z)
            )

        keys = jax.random.split(rng, n)
        return jnp.mean(jax.vmap(sample_nll)(keys), axis=0) + kl

    def generate_at_mean_given_z(self, params, z):
        return self.reconstruction_distribution.generate_at_mean(
            self._decode(params, z)
        )

    def generate_random_given_z(self, params, z, rng):
        return self.reconstruction_distribution.generate_random(
            rng, self._decode(params, z)
        )
