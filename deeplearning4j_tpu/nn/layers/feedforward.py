"""Feed-forward layers: Dense, Output, Loss, Activation, Dropout,
Embedding (reference: ``nn/layers/feedforward/**``, ``nn/layers/
OutputLayer.java``, ``BaseLayer.java`` preOutput = x·W + b).

The reference's BaseLayer does ``input.mmul(W).addiRowVector(b)`` as
two native calls; here it is one traced expression the XLA fuser turns
into a single MXU matmul with fused bias + activation.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import losses as losses_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import (
    FeedForwardLayerSpec,
    LayerSpec,
    register_layer,
)
from deeplearning4j_tpu.nn.weights import init_weights


@register_layer
@dataclass(frozen=True)
class DenseLayer(FeedForwardLayerSpec):
    """Fully connected layer (reference ``nn/conf/layers/DenseLayer`` +
    ``nn/layers/feedforward/dense/DenseLayer.java``)."""

    def supports_drop_connect(self) -> bool:
        return True

    def init_params(self, key, dtype=jnp.float32) -> dict:
        w = init_weights(
            key, (self.n_in, self.n_out), self.weight_init,
            fan_in=self.n_in, fan_out=self.n_out,
            distribution=self.dist, dtype=dtype,
        )
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": w, "b": b}

    def pre_output(self, params, x):
        return x @ params["W"] + params["b"]

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        params = self.maybe_drop_connect(params, train=train, rng=rng)
        from deeplearning4j_tpu.ops import (
            SUPPORTED_EPILOGUES,
            dispatch,
            matmul_block,
            matmul_block_ok,
        )

        act = self.activation.lower()
        # softmax heads (OutputLayer) stay on the XLA path: the row
        # reduction is not a per-element epilogue the kernel supports
        eligible = (
            x.ndim == 2
            and act in SUPPORTED_EPILOGUES
            and matmul_block_ok(
                x.shape[0], x.shape[1], params["W"].shape[1], x.dtype
            )
        )
        if dispatch.route("matmul_block", eligible):
            return matmul_block(
                x, params["W"], params["b"], activation=act
            ), state
        return self.activate_fn()(self.pre_output(params, x)), state


@dataclass(frozen=True)
class BaseOutputLayerSpec(DenseLayer):
    """Base for output layers carrying a loss function (reference
    ``nn/conf/layers/BaseOutputLayer.java``)."""

    loss: str = "MCXENT"

    def has_loss(self) -> bool:
        return True

    def compute_score(self, params, x, labels, mask=None, average=True):
        pre = self.pre_output(params, x)
        return losses_mod.score(
            self.loss, labels, pre, self.activation, mask, average
        )


@register_layer
@dataclass(frozen=True)
class OutputLayer(BaseOutputLayerSpec):
    """Standard classification/regression head (reference
    ``nn/layers/OutputLayer.java``). Default softmax+MCXENT."""

    activation: str = "softmax"


@register_layer
@dataclass(frozen=True)
class LossLayer(LayerSpec):
    """Loss without params: applies activation + loss to its input
    (reference ``nn/conf/layers/LossLayer.java``)."""

    loss: str = "MCXENT"
    activation: str = "identity"

    def has_loss(self) -> bool:
        return True

    def pre_output(self, params, x):
        return x

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return self.activate_fn()(x), state

    def compute_score(self, params, x, labels, mask=None, average=True):
        return losses_mod.score(self.loss, labels, x, self.activation, mask, average)


@register_layer
@dataclass(frozen=True)
class ActivationLayer(LayerSpec):
    """Pure activation (reference ``nn/conf/layers/ActivationLayer``).
    Shape-agnostic: consumes any input family unchanged (e.g. the ReLU
    after a residual ElementWiseVertex add in conv stacks)."""

    def input_kind(self) -> str:
        return "any"

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return self.activate_fn()(x), state


@register_layer
@dataclass(frozen=True)
class DropoutLayer(LayerSpec):
    """Standalone dropout. The reference has no DropoutLayer at this
    version (dropout is a per-layer flag applied in BaseLayer,
    SURVEY.md §2.1); provided for config convenience and Keras import."""

    activation: str = "identity"

    def input_kind(self) -> str:
        return "any"

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return self.maybe_dropout(x, train=train, rng=rng), state


@register_layer
@dataclass(frozen=True)
class EmbeddingLayer(FeedForwardLayerSpec):
    """Index -> row lookup (reference
    ``nn/layers/feedforward/embedding/EmbeddingLayer.java:41`` — input
    is a column of integer indices; forward is a row select, backward a
    scatter-add, both native XLA gather/scatter on TPU)."""

    activation: str = "identity"

    def init_params(self, key, dtype=jnp.float32) -> dict:
        w = init_weights(
            key, (self.n_in, self.n_out), self.weight_init,
            fan_in=self.n_in, fan_out=self.n_out,
            distribution=self.dist, dtype=dtype,
        )
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": w, "b": b}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        # x: [batch, 1] or [batch] of integer indices
        idx = x.reshape(-1).astype(jnp.int32)
        out = params["W"][idx] + params["b"]
        return self.activate_fn()(out), state


@register_layer
@dataclass(frozen=True)
class SparseEmbeddingLayer(EmbeddingLayer):
    """EmbeddingLayer whose ``[vocab, dim]`` table is a MESH resource:
    under ``DistributedTrainer`` the ``W`` rows shard ``P("data",
    None)`` over the data axis (the ``embeddings/`` subsystem's
    sharding shape), so table capacity — and, under GSPMD, the
    gradient/updater rows for it — scales with mesh width instead of
    one device's memory. The forward is the same gather as the base
    layer; the partitioning is declared by the TRAINER's rules keying
    on this type, which keeps the layer itself engine-agnostic (both
    engines build it through ``nn/core.py``: guard, telemetry, AOT
    ``_step_kind`` identity and checkpoint canonicalize-gather-then-
    reshard all treat ``W`` as an ordinary param).

    Eligibility fallbacks (sparse rows don't compose everywhere):
    megastep refuses models carrying this layer (``core.can_megastep``
    — the fused K-step scan would bake the row sharding into its
    carry), ``zero=True`` keeps ``W`` replicated (the flat ``P("data")``
    moment layout and the row layout can't both own the data axis),
    and the trainer always takes the GSPMD step (the shard_map step
    replicates every param per device). ``row_sharded=False`` opts a
    layer back into plain replicated behavior without a config change.
    """

    row_sharded: bool = True
