"""Layer implementations. Importing this package populates the JSON
subtype registry (reference analog: Jackson subtype scan)."""

from deeplearning4j_tpu.nn.layers.base import (  # noqa: F401
    LAYER_REGISTRY,
    FeedForwardLayerSpec,
    LayerSpec,
    layer_from_json,
    layer_to_json,
    register_layer,
)
from deeplearning4j_tpu.nn.layers.feedforward import (  # noqa: F401
    ActivationLayer,
    BaseOutputLayerSpec,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    LossLayer,
    OutputLayer,
    SparseEmbeddingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import (  # noqa: F401
    BatchNormalization,
    ConvolutionLayer,
    LocalResponseNormalization,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.recurrent import (  # noqa: F401
    GravesBidirectionalLSTM,
    GravesLSTM,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.layers.pretrain import (  # noqa: F401
    RBM,
    AutoEncoder,
)
from deeplearning4j_tpu.nn.layers.variational import (  # noqa: F401
    BernoulliReconstructionDistribution,
    CompositeReconstructionDistribution,
    ExponentialReconstructionDistribution,
    GaussianReconstructionDistribution,
    LossFunctionWrapper,
    ReconstructionDistribution,
    VariationalAutoencoder,
)
from deeplearning4j_tpu.nn.layers.attention import (  # noqa: F401
    LayerNormalization,
    MultiHeadSelfAttention,
    PositionalEncoding,
    TransformerBlock,
)
from deeplearning4j_tpu.nn.layers.moe import (  # noqa: F401
    MixtureOfExperts,
)
