"""Attention + layer-norm layers — net-new capability vs the
reference (which predates attention; SURVEY.md §5 "long-context"
names TBPTT/masking as its only sequence tools), added because
long-context is first-class in this framework. Follows the layer
conventions of the recurrent stack: sequence tensors are
[batch, features, time] (DL4J layout), masks [batch, time].

Single-shard attention lowers to two MXU matmuls with the softmax
fused between; for sequences sharded over a ``seq`` mesh axis the same
layer computes via ring attention
(``deeplearning4j_tpu.parallel.sequence.ring_attention``) when given a
``seq_axis``/``seq_axis_size`` — blockwise online softmax with K/V
blocks rotating over ICI."""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import (
    LayerSpec,
    register_layer,
)
from deeplearning4j_tpu.nn.weights import init_weights


@register_layer
@dataclass(frozen=True)
class MultiHeadSelfAttention(LayerSpec):
    """Multi-head self-attention over the time axis. ``causal`` masks
    future positions (decoder style); the feature mask argument masks
    padded timesteps (same convention as the recurrent layers)."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 4
    causal: bool = False
    activation: str = "identity"
    # when set, q/k/v arrive time-sharded over this mesh axis and the
    # layer computes ring attention instead of local attention
    seq_axis: str = ""
    seq_axis_size: int = 0

    def input_kind(self) -> str:
        return "recurrent"

    def with_input_type(self, it: InputType) -> "MultiHeadSelfAttention":
        changes = {}
        if self.n_in == 0:
            changes["n_in"] = it.size or it.flat_size()
        if self.n_out == 0:
            changes["n_out"] = it.size or it.flat_size()
        return dataclasses.replace(self, **changes) if changes else self

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def regularizable_params(self) -> tuple:
        return ("Wq", "Wk", "Wv", "Wo")

    def _head_dim(self) -> int:
        if self.n_in % self.n_heads != 0:
            raise ValueError(
                f"n_in={self.n_in} not divisible by "
                f"n_heads={self.n_heads}"
            )
        return self.n_in // self.n_heads

    def init_params(self, key, dtype=jnp.float32) -> dict:
        kq, kk, kv, ko = jax.random.split(key, 4)
        d = self.n_in
        mk = lambda k, shp: init_weights(  # noqa: E731
            k, shp, self.weight_init, fan_in=shp[0], fan_out=shp[1],
            distribution=self.dist, dtype=dtype,
        )
        return {
            "Wq": mk(kq, (d, d)),
            "Wk": mk(kk, (d, d)),
            "Wv": mk(kv, (d, d)),
            "Wo": mk(ko, (d, self.n_out)),
            "bo": jnp.full((self.n_out,), self.bias_init, dtype),
        }

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.ops import mha
        from deeplearning4j_tpu.parallel.sequence import ring_attention

        x = self.maybe_dropout(x, train=train, rng=rng)
        b, _, t = x.shape
        h, hd = self.n_heads, self._head_dim()
        xt = jnp.transpose(x, (0, 2, 1))               # [b, t, f]

        def heads(w):
            y = xt @ w                                  # [b, t, f]
            return jnp.transpose(
                y.reshape(b, t, h, hd), (0, 2, 1, 3)    # [b, h, t, hd]
            )

        q, k, v = heads(params["Wq"]), heads(params["Wk"]), heads(
            params["Wv"]
        )
        if self.seq_axis and self.seq_axis_size > 1:
            o = ring_attention(
                q, k, v, axis_name=self.seq_axis,
                axis_size=self.seq_axis_size, causal=self.causal,
                mask=mask,
            )
        else:
            # mha dispatches to the Pallas flash kernel on TPU
            o = mha(q, k, v, causal=self.causal, mask=mask)
        o = jnp.transpose(o, (0, 2, 1, 3)).reshape(b, t, h * hd)
        y = o @ params["Wo"] + params["bo"]             # [b, t, n_out]
        if mask is not None:
            y = y * mask[:, :, None]
        y = self.activate_fn()(y)
        return jnp.transpose(y, (0, 2, 1)), state       # [b, n_out, t]


@register_layer
@dataclass(frozen=True)
class LayerNormalization(LayerSpec):
    """Layer norm over the feature axis for [b, f] or [b, f, t]
    tensors (companion to attention; the reference's only norm is
    BatchNormalization)."""

    n_out: int = 0
    # named `eps` (not `epsilon`) to avoid shadowing the optimizer
    # epsilon inherited from LayerSpec — same as BatchNormalization
    eps: float = 1e-5
    activation: str = "identity"

    def input_kind(self) -> str:
        return "any"

    def with_input_type(self, it: InputType) -> "LayerNormalization":
        if self.n_out == 0:
            return dataclasses.replace(
                self, n_out=it.size or it.flat_size()
            )
        return self

    def regularizable_params(self) -> tuple:
        return ()

    def init_params(self, key, dtype=jnp.float32) -> dict:
        return {
            "gamma": jnp.ones((self.n_out,), dtype),
            "beta": jnp.zeros((self.n_out,), dtype),
        }

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        # feature axis is 1 for both [b, f] and [b, f, t]
        mean = jnp.mean(x, axis=1, keepdims=True)
        var = jnp.var(x, axis=1, keepdims=True)
        xn = (x - mean) / jnp.sqrt(var + self.eps)
        g = params["gamma"]
        bta = params["beta"]
        if x.ndim == 3:
            g = g[:, None]
            bta = bta[:, None]
        return self.activate_fn()(xn * g + bta), state
