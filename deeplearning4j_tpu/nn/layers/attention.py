"""Attention + layer-norm layers — net-new capability vs the
reference (which predates attention; SURVEY.md §5 "long-context"
names TBPTT/masking as its only sequence tools), added because
long-context is first-class in this framework. Follows the layer
conventions of the recurrent stack: sequence tensors are
[batch, features, time] (DL4J layout), masks [batch, time].

Single-shard attention lowers to two MXU matmuls with the softmax
fused between; for sequences sharded over a ``seq`` mesh axis the same
layer computes via ring attention
(``deeplearning4j_tpu.parallel.sequence.ring_attention``) when given a
``seq_axis``/``seq_axis_size`` — blockwise online softmax with K/V
blocks rotating over ICI."""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import (
    LayerSpec,
    register_layer,
)
from deeplearning4j_tpu.nn.weights import init_weights


@register_layer
@dataclass(frozen=True)
class MultiHeadSelfAttention(LayerSpec):
    """Multi-head self-attention over the time axis. ``causal`` masks
    future positions (decoder style); the feature mask argument masks
    padded timesteps (same convention as the recurrent layers)."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 4
    causal: bool = False
    activation: str = "identity"
    # when set, q/k/v arrive time-sharded over this mesh axis and the
    # layer computes ring attention instead of local attention
    seq_axis: str = ""
    seq_axis_size: int = 0
    # max total timesteps for incremental decoding (the rnnTimeStep
    # analog): the KV cache is a fixed [b, h, kv_cache, hd] buffer so
    # streaming stays jit-static
    kv_cache: int = 1024

    def input_kind(self) -> str:
        return "recurrent"

    # -- streaming (rnn_time_step) contract -----------------------------

    def streams_state(self) -> bool:
        return True

    def can_stream(self) -> bool:
        # a non-causal layer needs future timesteps — cannot stream
        return self.causal

    def stream_state_keys(self) -> tuple:
        return ("k_cache", "v_cache", "pos")

    def stream_capacity(self):
        return self.kv_cache

    def init_stream_state(self, batch: int, dtype) -> dict:
        hd = self._head_dim()
        shape = (batch, self.n_heads, self.kv_cache, hd)
        return {
            "k_cache": jnp.zeros(shape, dtype),
            "v_cache": jnp.zeros(shape, dtype),
            "pos": jnp.zeros((), jnp.int32),
        }

    def with_input_type(self, it: InputType) -> "MultiHeadSelfAttention":
        changes = {}
        if self.n_in == 0:
            changes["n_in"] = it.size or it.flat_size()
        if self.n_out == 0:
            changes["n_out"] = it.size or it.flat_size()
        return dataclasses.replace(self, **changes) if changes else self

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def regularizable_params(self) -> tuple:
        return ("Wq", "Wk", "Wv", "Wo")

    def _head_dim(self) -> int:
        if self.n_in % self.n_heads != 0:
            raise ValueError(
                f"n_in={self.n_in} not divisible by "
                f"n_heads={self.n_heads}"
            )
        return self.n_in // self.n_heads

    def init_params(self, key, dtype=jnp.float32) -> dict:
        kq, kk, kv, ko = jax.random.split(key, 4)
        d = self.n_in
        mk = lambda k, shp: init_weights(  # noqa: E731
            k, shp, self.weight_init, fan_in=shp[0], fan_out=shp[1],
            distribution=self.dist, dtype=dtype,
        )
        return {
            "Wq": mk(kq, (d, d)),
            "Wk": mk(kk, (d, d)),
            "Wv": mk(kv, (d, d)),
            "Wo": mk(ko, (d, self.n_out)),
            "bo": jnp.full((self.n_out,), self.bias_init, dtype),
        }

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.ops import mha
        from deeplearning4j_tpu.parallel.sequence import ring_attention

        x = self.maybe_dropout(x, train=train, rng=rng)
        b, _, t = x.shape
        h, hd = self.n_heads, self._head_dim()
        xt = jnp.transpose(x, (0, 2, 1))               # [b, t, f]

        def heads(w):
            y = xt @ w                                  # [b, t, f]
            return jnp.transpose(
                y.reshape(b, t, h, hd), (0, 2, 1, 3)    # [b, h, t, hd]
            )

        q, k, v = heads(params["Wq"]), heads(params["Wk"]), heads(
            params["Wv"]
        )
        if "k_cache" in state:
            # incremental decode: append this chunk's K/V to the cache
            # and attend over the filled prefix (fixed cache shape ->
            # jit-static; reference analog: rnnTimeStep's stateMap)
            from jax import lax as _lax

            pos = state["pos"]
            kc = _lax.dynamic_update_slice(
                state["k_cache"], k.astype(state["k_cache"].dtype),
                (0, 0, pos, 0),
            )
            vc = _lax.dynamic_update_slice(
                state["v_cache"], v.astype(state["v_cache"].dtype),
                (0, 0, pos, 0),
            )
            scale = 1.0 / jnp.sqrt(jnp.asarray(hd, q.dtype))
            s = jnp.einsum("bhqd,bhkd->bhqk", q, kc) * scale
            key_idx = jnp.arange(self.kv_cache)[None, None, None, :]
            q_idx = (pos + jnp.arange(t))[None, None, :, None]
            s = jnp.where(key_idx <= q_idx, s, -1e9)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, vc)
            new_state = {
                **state, "k_cache": kc, "v_cache": vc,
                "pos": pos + t,
            }
            o = jnp.transpose(o, (0, 2, 1, 3)).reshape(b, t, h * hd)
            y = o @ params["Wo"] + params["bo"]
            y = self.activate_fn()(y)
            return jnp.transpose(y, (0, 2, 1)), new_state
        if self.seq_axis and self.seq_axis_size > 1:
            o = ring_attention(
                q, k, v, axis_name=self.seq_axis,
                axis_size=self.seq_axis_size, causal=self.causal,
                mask=mask,
            )
        else:
            # mha dispatches to the Pallas flash kernel on TPU
            o = mha(q, k, v, causal=self.causal, mask=mask)
        o = jnp.transpose(o, (0, 2, 1, 3)).reshape(b, t, h * hd)
        y = o @ params["Wo"] + params["bo"]             # [b, t, n_out]
        if mask is not None:
            y = y * mask[:, :, None]
        y = self.activate_fn()(y)
        return jnp.transpose(y, (0, 2, 1)), state       # [b, n_out, t]


@register_layer
@dataclass(frozen=True)
class TransformerBlock(LayerSpec):
    """Pre-norm transformer block: LN -> multi-head self-attention ->
    residual, LN -> FFN (or Switch-MoE) -> residual. Net-new vs the
    reference, composing the attention/norm/MoE layers into the
    standard long-context building block. Sequence layout follows the
    recurrent stack: [batch, features, time], mask [batch, time].

    ``n_experts > 0`` swaps the dense FFN for a Switch
    mixture-of-experts (top-1, capacity-dropped tokens ride the
    residual)."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 4
    ffn_hidden: int = 0   # 0 -> 4 * n_in
    causal: bool = True
    n_experts: int = 0    # 0 -> dense FFN; >0 -> Switch MoE
    capacity_factor: float = 1.25
    activation: str = "identity"
    seq_axis: str = ""
    seq_axis_size: int = 0
    kv_cache: int = 1024  # incremental-decode cache (see MHSA)

    def input_kind(self) -> str:
        return "recurrent"

    def with_input_type(self, it: InputType) -> "TransformerBlock":
        changes = {}
        if self.n_in == 0:
            changes["n_in"] = it.size or it.flat_size()
        width = changes.get("n_in", self.n_in)
        if self.n_out == 0:
            changes["n_out"] = width
        if (changes.get("n_out", self.n_out)) != width:
            from deeplearning4j_tpu.exceptions import (
                DL4JInvalidConfigException,
            )

            raise DL4JInvalidConfigException(
                "TransformerBlock is residual: n_out must equal n_in"
            )
        return dataclasses.replace(self, **changes) if changes else self

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def regularizable_params(self) -> tuple:
        return ("Wq", "Wk", "Wv", "Wo", "w_ff1", "w_ff2", "w1", "w2")

    def _attn(self) -> MultiHeadSelfAttention:
        return MultiHeadSelfAttention(
            n_in=self.n_in, n_out=self.n_in, n_heads=self.n_heads,
            causal=self.causal, seq_axis=self.seq_axis,
            seq_axis_size=self.seq_axis_size, kv_cache=self.kv_cache,
            weight_init=self.weight_init, dist=self.dist,
        )

    # -- streaming (rnn_time_step) contract: delegate to the attention
    # sublayer (LN/FFN are per-position and carry nothing)

    def streams_state(self) -> bool:
        return True

    def can_stream(self) -> bool:
        return self.causal

    def stream_state_keys(self) -> tuple:
        return ("k_cache", "v_cache", "pos")

    def stream_capacity(self):
        return self.kv_cache

    def init_stream_state(self, batch: int, dtype) -> dict:
        return self._attn().init_stream_state(batch, dtype)

    def _ln(self) -> "LayerNormalization":
        return LayerNormalization(n_out=self.n_in)

    def _moe(self):
        from deeplearning4j_tpu.nn.layers.moe import MixtureOfExperts

        return MixtureOfExperts(
            n_in=self.n_in, n_out=self.n_in,
            n_experts=self.n_experts,
            hidden_size=self.ffn_hidden or 4 * self.n_in,
            capacity_factor=self.capacity_factor,
            activation="identity",
        )

    def init_params(self, key, dtype=jnp.float32) -> dict:
        k_attn, k_ff1, k_ff2, k_moe = jax.random.split(key, 4)
        d = self.n_in
        h = self.ffn_hidden or 4 * d
        p = {}
        p.update(self._attn().init_params(k_attn, dtype))
        p["ln1_gamma"] = jnp.ones((d,), dtype)
        p["ln1_beta"] = jnp.zeros((d,), dtype)
        p["ln2_gamma"] = jnp.ones((d,), dtype)
        p["ln2_beta"] = jnp.zeros((d,), dtype)
        if self.n_experts > 0:
            p.update(self._moe().init_params(k_moe, dtype))
        else:
            p["w_ff1"] = init_weights(
                k_ff1, (d, h), self.weight_init, fan_in=d, fan_out=h,
                distribution=self.dist, dtype=dtype,
            )
            p["b_ff1"] = jnp.zeros((h,), dtype)
            p["w_ff2"] = init_weights(
                k_ff2, (h, d), self.weight_init, fan_in=h, fan_out=d,
                distribution=self.dist, dtype=dtype,
            )
            p["b_ff2"] = jnp.zeros((d,), dtype)
        return p

    def _layernorm(self, x, gamma, beta, eps=1e-5):
        mean = jnp.mean(x, axis=1, keepdims=True)
        var = jnp.var(x, axis=1, keepdims=True)
        return (x - mean) / jnp.sqrt(var + eps) * gamma[:, None] \
            + beta[:, None]

    def apply(self, params, x, state, *, train=False, rng=None,
              mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        # attention sublayer (pre-norm); streaming KV-cache state (if
        # any) passes through to the attention and back out
        h1 = self._layernorm(x, params["ln1_gamma"], params["ln1_beta"])
        attn_params = {
            k: params[k] for k in ("Wq", "Wk", "Wv", "Wo", "bo")
        }
        a, state = self._attn().apply(
            attn_params, h1, state, train=False, rng=None, mask=mask
        )
        x = x + a
        # FFN / MoE sublayer (pre-norm)
        h2 = self._layernorm(x, params["ln2_gamma"], params["ln2_beta"])
        if self.n_experts > 0:
            from deeplearning4j_tpu.parallel.expert import (
                moe_ffn_reference,
            )

            moe_params = {
                k: params[k] for k in ("router", "w1", "b1", "w2", "b2")
            }
            b, fdim, t = h2.shape
            tokens = h2.transpose(0, 2, 1).reshape(b * t, fdim)
            token_mask = (
                mask.reshape(b * t) if mask is not None else None
            )
            upd = moe_ffn_reference(
                moe_params, tokens, self.capacity_factor, token_mask
            )
            upd = upd.reshape(b, t, fdim).transpose(0, 2, 1)
            x = x + upd
        else:
            ht = jnp.transpose(h2, (0, 2, 1))           # [b, t, f]
            ff = jax.nn.gelu(ht @ params["w_ff1"] + params["b_ff1"])
            ff = ff @ params["w_ff2"] + params["b_ff2"]
            ff = jnp.transpose(ff, (0, 2, 1))           # [b, f, t]
            if mask is not None:
                ff = ff * mask[:, None, :]
            x = x + ff
        return self.activate_fn()(x), state


@register_layer
@dataclass(frozen=True)
class LayerNormalization(LayerSpec):
    """Layer norm over the feature axis for [b, f] or [b, f, t]
    tensors (companion to attention; the reference's only norm is
    BatchNormalization)."""

    n_out: int = 0
    # named `eps` (not `epsilon`) to avoid shadowing the optimizer
    # epsilon inherited from LayerSpec — same as BatchNormalization
    eps: float = 1e-5
    activation: str = "identity"

    def input_kind(self) -> str:
        return "any"

    def with_input_type(self, it: InputType) -> "LayerNormalization":
        if self.n_out == 0:
            return dataclasses.replace(
                self, n_out=it.size or it.flat_size()
            )
        return self

    def regularizable_params(self) -> tuple:
        return ()

    def init_params(self, key, dtype=jnp.float32) -> dict:
        return {
            "gamma": jnp.ones((self.n_out,), dtype),
            "beta": jnp.zeros((self.n_out,), dtype),
        }

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        # feature axis is 1 for both [b, f] and [b, f, t]
        mean = jnp.mean(x, axis=1, keepdims=True)
        var = jnp.var(x, axis=1, keepdims=True)
        xn = (x - mean) / jnp.sqrt(var + self.eps)
        g = params["gamma"]
        bta = params["beta"]
        if x.ndim == 3:
            g = g[:, None]
            bta = bta[:, None]
        return self.activate_fn()(xn * g + bta), state


@register_layer
@dataclass(frozen=True)
class PositionalEncoding(LayerSpec):
    """Sinusoidal positional encoding added to [b, n, t] activations
    (Vaswani et al. 2017) — parameter-free, any sequence length, so it
    composes with the jit static-shape contract. Attention is
    permutation-equivariant without it; place after the input
    projection in decoder-only stacks."""

    max_wavelength: float = 10000.0

    def input_kind(self) -> str:
        return "recurrent"

    # -- streaming: carry the absolute position offset ------------------

    def streams_state(self) -> bool:
        return True

    def stream_state_keys(self) -> tuple:
        return ("pos",)

    def init_stream_state(self, batch: int, dtype) -> dict:
        return {"pos": jnp.zeros((), jnp.int32)}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        n, t = x.shape[1], x.shape[2]
        if "pos" in state:
            off = state["pos"]
            pos = (off + jnp.arange(t)).astype(x.dtype)
            state = {**state, "pos": off + t}
        else:
            pos = jnp.arange(t, dtype=x.dtype)
        i = jnp.arange(n)
        freq = jnp.asarray(self.max_wavelength, x.dtype) ** (
            -((i // 2) * 2 / n).astype(x.dtype)
        )
        angle = freq[:, None] * pos[None, :]              # [n, t]
        pe = jnp.where(
            (i % 2 == 0)[:, None], jnp.sin(angle), jnp.cos(angle)
        )
        return x + pe[None].astype(x.dtype), state
