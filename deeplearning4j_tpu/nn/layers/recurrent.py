"""Recurrent stack (reference: ``nn/layers/recurrent/GravesLSTM.java``,
``GravesBidirectionalLSTM.java``, shared math in ``LSTMHelpers.java:56``
(activateHelper, per-timestep loop with fused ifog gate mmul at
``:159``) and ``RnnOutputLayer``).

TPU-first design:
- The reference loops over timesteps in Java, launching a fused-gate
  mmul per step. Here the input projection ``x·W`` for ALL timesteps is
  ONE big MXU matmul (``[t*b, nIn]·[nIn, 4n]``) hoisted out of the
  recurrence; only the sequential ``h·RW`` stays inside ``lax.scan``,
  which XLA compiles to a single fused while-loop — no per-step
  dispatch.
- State (h, c) is carried functionally: standard training resets it
  per minibatch, TBPTT threads it across chunks, ``rnnTimeStep``
  streams it across calls (reference ``stateMap``/``tBpttStateMap``).
- Variable-length sequences use a [batch, time] mask: masked steps
  pass state through unchanged and output zeros (reference masking
  exercised by ``GradientCheckTestsMasking``).

Gate packing is ifog (input, forget, output, block-input) like the
reference; peephole weights (Graves-style) are separate named params
``pI``/``pF``/``pO`` rather than packed into RW's trailing columns —
documented divergence for a cleaner pytree.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import activations as act_mod
from deeplearning4j_tpu.nn import losses as losses_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import LayerSpec, register_layer
from deeplearning4j_tpu.nn.layers.feedforward import BaseOutputLayerSpec
from deeplearning4j_tpu.nn.weights import init_weights


def _lstm_params(key, n_in, n_out, weight_init, dist, forget_bias, dtype,
                 peephole: bool):
    k1, k2, k3 = jax.random.split(key, 3)
    p = {
        "W": init_weights(k1, (n_in, 4 * n_out), weight_init,
                          fan_in=n_in, fan_out=n_out,
                          distribution=dist, dtype=dtype),
        "RW": init_weights(k2, (n_out, 4 * n_out), weight_init,
                           fan_in=n_out, fan_out=n_out,
                           distribution=dist, dtype=dtype),
        "b": jnp.concatenate([
            jnp.zeros((n_out,), dtype),                    # i
            jnp.full((n_out,), forget_bias, dtype),        # f
            jnp.zeros((2 * n_out,), dtype),                # o, g
        ]),
    }
    if peephole:
        p["pI"] = jnp.zeros((n_out,), dtype)
        p["pF"] = jnp.zeros((n_out,), dtype)
        p["pO"] = jnp.zeros((n_out,), dtype)
    return p


def _lstm_scan(p, x_bnt, h0, c0, mask_bt, gate_fn, act_fn, peephole,
               reverse: bool = False):
    """Run the LSTM over [b, nIn, t] input; returns ([b, n, t] outputs,
    (hT, cT))."""
    n = h0.shape[-1]
    # [b, nIn, t] -> [t, b, nIn]
    x_tbi = jnp.transpose(x_bnt, (2, 0, 1))
    if reverse:
        x_tbi = jnp.flip(x_tbi, axis=0)
    # fused ifog input projection for all timesteps: one MXU matmul
    xin = x_tbi @ p["W"] + p["b"]  # [t, b, 4n]
    if mask_bt is not None:
        m_tb = jnp.transpose(mask_bt, (1, 0))[:, :, None]  # [t, b, 1]
        if reverse:
            m_tb = jnp.flip(m_tb, axis=0)
    else:
        m_tb = None

    # standard sigmoid/tanh cells on TPU route through the fused
    # Pallas kernel (one VMEM-resident matmul+gates program per step)
    from deeplearning4j_tpu.nn import activations as _act
    from deeplearning4j_tpu.ops import lstm_cell_diff, use_pallas_lstm
    from deeplearning4j_tpu.ops.lstm_cell import (
        lstm_sequence,
        lstm_sequence_ok,
    )

    fused = (
        use_pallas_lstm()
        and gate_fn is _act.get("sigmoid")
        and act_fn is _act.get("tanh")
    )
    # whole-sequence kernel: RW stays VMEM-resident across ALL
    # timesteps instead of being re-fetched from HBM per step — the
    # per-step reload is the HBM roofline that caps the scan cell
    # (artifacts/lstm_roofline_r5.md). Standard gates, no peephole/
    # mask, RW small enough for VMEM.
    if (fused and not peephole and m_tb is None
            and lstm_sequence_ok(n, 4 * n, p["RW"].dtype,
                                 x_bnt.shape[0])):
        outs, hT, cT = lstm_sequence(
            xin, h0, c0, p["RW"]
        )
        if reverse:
            outs = jnp.flip(outs, axis=0)
        return jnp.transpose(outs, (1, 2, 0)), (hT, cT)

    def cell(carry, inp):
        h, c = carry
        if m_tb is None:
            xproj = inp
            m = None
        else:
            xproj, m = inp
        if fused:
            peeps = (p["pI"], p["pF"], p["pO"]) if peephole else None
            h_new, c_new = lstm_cell_diff(xproj, h, c, p["RW"], peeps)
        else:
            z = xproj + h @ p["RW"]
            zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
            if peephole:
                zi = zi + c * p["pI"]
                zf = zf + c * p["pF"]
            i = gate_fn(zi)
            f = gate_fn(zf)
            g = act_fn(zg)
            c_new = f * c + i * g
            if peephole:
                zo = zo + c_new * p["pO"]
            o = gate_fn(zo)
            h_new = o * act_fn(c_new)
        if m is not None:
            h_new = m * h_new + (1.0 - m) * h
            c_new = m * c_new + (1.0 - m) * c
            out = m * h_new
        else:
            out = h_new
        return (h_new, c_new), out

    xs = xin if m_tb is None else (xin, m_tb)
    (hT, cT), outs = lax.scan(cell, (h0, c0), xs)
    if reverse:
        outs = jnp.flip(outs, axis=0)
    # [t, b, n] -> [b, n, t]
    return jnp.transpose(outs, (1, 2, 0)), (hT, cT)


@register_layer
@dataclass(frozen=True)
class GravesLSTM(LayerSpec):
    """Graves-style LSTM with peepholes (reference ``GravesLSTM.java:40``
    + ``LSTMHelpers``)."""

    n_in: int = 0
    n_out: int = 0
    activation: str = "tanh"
    gate_activation: str = "sigmoid"
    forget_gate_bias_init: float = 1.0
    peephole: bool = True

    def input_kind(self) -> str:
        return "recurrent"

    def is_recurrent(self) -> bool:
        return True

    def with_input_type(self, it: InputType) -> "GravesLSTM":
        if self.n_in == 0:
            return dataclasses.replace(self, n_in=it.size or it.flat_size())
        return self

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def regularizable_params(self) -> tuple:
        return ("W", "RW")

    def init_params(self, key, dtype=jnp.float32) -> dict:
        return _lstm_params(
            key, self.n_in, self.n_out, self.weight_init, self.dist,
            self.forget_gate_bias_init, dtype, self.peephole,
        )

    def _carry_init(self, batch, dtype):
        z = jnp.zeros((batch, self.n_out), dtype)
        return z, z

    def init_stream_state(self, batch: int, dtype) -> dict:
        """Zero h/c carry as a state pytree — what ``apply`` returns
        between streaming/TBPTT chunks. Distinct buffers: jitted steps
        donate the state, and one array donated twice is an XLA
        error."""
        return {
            "h": jnp.zeros((batch, self.n_out), dtype),
            "c": jnp.zeros((batch, self.n_out), dtype),
        }

    def supports_drop_connect(self) -> bool:
        return True

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        # reference LSTMHelpers.java:93 drops the INPUT weights only
        params = self.maybe_drop_connect(params, train=train, rng=rng,
                                         keys=("W",))
        if "h" in state:
            h0, c0 = state["h"], state["c"]
        else:
            h0, c0 = self._carry_init(x.shape[0], x.dtype)
        outs, (hT, cT) = _lstm_scan(
            params, x, h0, c0, mask,
            act_mod.get(self.gate_activation), act_mod.get(self.activation),
            self.peephole,
        )
        return outs, {"h": hT, "c": cT}


@register_layer
@dataclass(frozen=True)
class GravesBidirectionalLSTM(GravesLSTM):
    """Bidirectional Graves LSTM (reference
    ``GravesBidirectionalLSTM.java``): forward + backward passes over
    the sequence, combined by ``mode`` (reference combines by add)."""

    mode: str = "add"  # add | concat | average | mul

    def output_type(self, it: InputType) -> InputType:
        n = 2 * self.n_out if self.mode == "concat" else self.n_out
        return InputType.recurrent(n, it.timeseries_length)

    def regularizable_params(self) -> tuple:
        return ("WF", "RWF", "WB", "RWB")

    def init_params(self, key, dtype=jnp.float32) -> dict:
        kf, kb = jax.random.split(key)
        fwd = _lstm_params(kf, self.n_in, self.n_out, self.weight_init,
                           self.dist, self.forget_gate_bias_init, dtype,
                           self.peephole)
        bwd = _lstm_params(kb, self.n_in, self.n_out, self.weight_init,
                           self.dist, self.forget_gate_bias_init, dtype,
                           self.peephole)
        out = {k + "F": v for k, v in fwd.items()}
        out.update({k + "B": v for k, v in bwd.items()})
        return out

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        params = self.maybe_drop_connect(params, train=train, rng=rng,
                                         keys=("WF", "WB"))
        h0, c0 = self._carry_init(x.shape[0], x.dtype)
        gate_fn = act_mod.get(self.gate_activation)
        act_fn = act_mod.get(self.activation)
        pf = {k[:-1]: v for k, v in params.items() if k.endswith("F")}
        pb = {k[:-1]: v for k, v in params.items() if k.endswith("B")}
        of, _ = _lstm_scan(pf, x, h0, c0, mask, gate_fn, act_fn,
                           self.peephole)
        ob, _ = _lstm_scan(pb, x, h0, c0, mask, gate_fn, act_fn,
                           self.peephole, reverse=True)
        if self.mode == "add":
            y = of + ob
        elif self.mode == "average":
            y = 0.5 * (of + ob)
        elif self.mode == "mul":
            y = of * ob
        elif self.mode == "concat":
            y = jnp.concatenate([of, ob], axis=1)
        else:
            raise ValueError(f"Unknown bidirectional mode '{self.mode}'")
        # Bidirectional layers have no streaming carry (the backward
        # pass needs the full sequence) — reference behaves the same.
        return y, state

    def is_recurrent(self) -> bool:
        return False  # no streaming carry

    def can_stream(self) -> bool:
        return False  # backward pass needs the full sequence


@register_layer
@dataclass(frozen=True)
class RnnOutputLayer(BaseOutputLayerSpec):
    """Per-timestep dense + loss on [b, n, t] activations (reference
    ``nn/layers/recurrent/RnnOutputLayer.java``)."""

    activation: str = "softmax"

    def input_kind(self) -> str:
        return "recurrent"

    def with_input_type(self, it: InputType) -> "RnnOutputLayer":
        if self.n_in == 0:
            return dataclasses.replace(self, n_in=it.size or it.flat_size())
        return self

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def pre_output(self, params, x):
        # [b, nIn, t] x [nIn, nOut] -> [b, nOut, t]
        return jnp.einsum("bit,io->bot", x, params["W"]) + \
            params["b"][None, :, None]

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        # reference RnnOutputLayer.java:167
        params = self.maybe_drop_connect(params, train=train, rng=rng)
        pre = self.pre_output(params, x)
        if self.activation == "softmax":
            y = jax.nn.softmax(pre, axis=1)  # class axis
        else:
            y = self.activate_fn()(pre)
        return y, state
