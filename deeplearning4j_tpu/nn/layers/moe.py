"""Mixture-of-Experts layer for the layer-stack API (net-new vs the
reference — SURVEY.md §2.4 has no EP/MoE; designed to slot into
``MultiLayerNetwork``/``ComputationGraph`` like any feed-forward
layer).

Single-chip semantics use the dense Switch dispatch from
:mod:`deeplearning4j_tpu.parallel.expert` (top-1 routing, per-batch
capacity, dropped tokens pass through as zeros via the residual add).
For mesh execution shard the expert-stacked params over an ``expert``
axis with ``ExpertParallelMoE`` — same math, all_to_all token
exchange.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import register_layer
from deeplearning4j_tpu.nn.layers.feedforward import FeedForwardLayerSpec


@register_layer
@dataclass(frozen=True)
class MixtureOfExperts(FeedForwardLayerSpec):
    """Switch-style MoE FFN block: router -> top-1 expert two-layer
    FFN -> combine, with a residual connection (so capacity-dropped
    tokens keep their input representation). n_in == n_out."""

    n_experts: int = 4
    hidden_size: int = 0  # 0 -> 4 * n_in
    capacity_factor: float = 1.25

    def with_input_type(self, input_type):
        import dataclasses

        layer = super().with_input_type(input_type)
        if not layer.n_out:  # residual block: width preserved
            layer = dataclasses.replace(layer, n_out=layer.n_in)
        if layer.n_out and layer.n_in and layer.n_out != layer.n_in:
            from deeplearning4j_tpu.exceptions import (
                DL4JInvalidConfigException,
            )

            raise DL4JInvalidConfigException(
                "MixtureOfExperts is residual: n_out must equal n_in "
                f"(got {layer.n_in} -> {layer.n_out})"
            )
        return layer

    def _hidden(self) -> int:
        return self.hidden_size or 4 * self.n_in

    def init_params(self, key, dtype=jnp.float32) -> dict:
        from deeplearning4j_tpu.parallel.expert import init_moe_params

        p = init_moe_params(
            key, self.n_in, self._hidden(), self.n_experts, dtype
        )
        return p

    def regularizable_params(self) -> tuple:
        return ("w1", "w2")

    def apply(self, params, x, state, *, train=False, rng=None,
              mask=None):
        from deeplearning4j_tpu.parallel.expert import moe_ffn_reference

        x = self.maybe_dropout(x, train=train, rng=rng)
        seq = x.ndim == 3
        if seq:  # [b, f, t] recurrent layout -> tokens [b*t, f]
            b, f, t = x.shape
            tokens = x.transpose(0, 2, 1).reshape(b * t, f)
            token_mask = (
                mask.reshape(b * t) if mask is not None else None
            )
        else:
            tokens = x
            token_mask = mask
        # padding tokens: no routing (capacity untouched), zero expert
        # update through the residual, zeroed output like the sibling
        # attention layer
        out = tokens + moe_ffn_reference(
            params, tokens, self.capacity_factor, token_mask
        )
        out = self.activate_fn()(out)
        if token_mask is not None:
            out = out * token_mask[:, None].astype(out.dtype)
        if seq:
            out = out.reshape(b, t, f).transpose(0, 2, 1)
        return out, state

    def aux_loss(self, params, x) -> jax.Array:
        """Load-balancing auxiliary loss for custom training loops."""
        from deeplearning4j_tpu.parallel.expert import (
            aux_load_balance_loss,
        )

        tokens = (
            x.transpose(0, 2, 1).reshape(-1, x.shape[1])
            if x.ndim == 3 else x
        )
        return aux_load_balance_loss(tokens @ params["router"])
