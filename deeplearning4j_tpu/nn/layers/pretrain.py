"""Layer-wise pretraining layers: denoising AutoEncoder and RBM
(reference: ``nn/layers/feedforward/autoencoder/AutoEncoder.java``,
``nn/layers/feedforward/rbm/RBM.java:101,:200`` contrastive divergence
+ Gibbs sampling; config beans ``nn/conf/layers/AutoEncoder.java``,
``nn/conf/layers/RBM.java:83-86`` VisibleUnit/HiddenUnit enums).

TPU-first notes:
- The autoencoder's corrupt→encode→decode→loss is one traced
  expression; tied decoder weights (W^T) stay a single MXU matmul.
- The RBM's CD-k gradient (positive phase minus negative phase) is
  expressed through the free-energy identity: grad of
  ``mean(F(v_data) - F(v_model))`` with the Gibbs chain under
  ``stop_gradient`` equals the classic CD update for binary units, so
  ``jax.grad`` produces the reference's hand-derived update without a
  second code path. The k Gibbs steps run in ``lax.fori_loop`` (static
  trip count, PRNG threaded) — one compiled kernel, no host round
  trips per step.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import losses as losses_mod
from deeplearning4j_tpu.nn.layers.base import register_layer
from deeplearning4j_tpu.nn.layers.feedforward import FeedForwardLayerSpec
from deeplearning4j_tpu.nn.weights import init_weights


@register_layer
@dataclass(frozen=True)
class AutoEncoder(FeedForwardLayerSpec):
    """Denoising autoencoder with tied weights (reference
    ``nn/layers/feedforward/autoencoder/AutoEncoder.java``: encode
    sigmoid(xW+b), decode sigmoid(hW'+vb), masking-noise corruption
    ``corruptionLevel``)."""

    corruption_level: float = 0.3
    loss: str = "XENT"

    def is_pretrainable(self) -> bool:
        return True

    def init_params(self, key, dtype=jnp.float32) -> dict:
        w = init_weights(
            key, (self.n_in, self.n_out), self.weight_init,
            fan_in=self.n_in, fan_out=self.n_out,
            distribution=self.dist, dtype=dtype,
        )
        return {
            "W": w,
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
            "vb": jnp.full((self.n_in,), self.bias_init, dtype),
        }

    def encode(self, params, x):
        return self.activate_fn()(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return self.activate_fn()(h @ params["W"].T + params["vb"])

    def supports_drop_connect(self) -> bool:
        return True

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        # reference BasePretrainNetwork inherits BaseLayer.preOutput's
        # DropConnect (BaseLayer.java:365)
        params = self.maybe_drop_connect(params, train=train, rng=rng)
        return self.encode(params, x), state

    def pretrain_loss(self, params, x, rng):
        corrupted = x
        if rng is not None and self.corruption_level > 0.0:
            keep = jax.random.bernoulli(
                rng, 1.0 - self.corruption_level, x.shape
            )
            corrupted = jnp.where(keep, x, 0.0)
        h = self.encode(params, corrupted)
        recon_pre = h @ params["W"].T + params["vb"]
        return losses_mod.score(
            self.loss, x, recon_pre, self.activation, None, True
        )


@register_layer
@dataclass(frozen=True)
class RBM(FeedForwardLayerSpec):
    """Restricted Boltzmann machine trained by CD-k (reference
    ``nn/layers/feedforward/rbm/RBM.java``: ``contrastiveDivergence``
    at ``:101``, ``gibbhVh`` sampling chain at ``:200``).

    ``visible_unit``: BINARY | GAUSSIAN; ``hidden_unit``: BINARY |
    RECTIFIED (reference enums ``RBM.java:83-86`` also list SOFTMAX —
    rarely used; unsupported here and rejected at init).
    """

    visible_unit: str = "BINARY"
    hidden_unit: str = "BINARY"
    k: int = 1

    def is_pretrainable(self) -> bool:
        return True

    def init_params(self, key, dtype=jnp.float32) -> dict:
        if self.visible_unit not in ("BINARY", "GAUSSIAN"):
            raise ValueError(f"Unsupported visible_unit {self.visible_unit}")
        if self.hidden_unit not in ("BINARY", "RECTIFIED"):
            raise ValueError(f"Unsupported hidden_unit {self.hidden_unit}")
        w = init_weights(
            key, (self.n_in, self.n_out), self.weight_init,
            fan_in=self.n_in, fan_out=self.n_out,
            distribution=self.dist, dtype=dtype,
        )
        return {
            "W": w,
            "b": jnp.full((self.n_out,), self.bias_init, dtype),   # hidden
            "vb": jnp.full((self.n_in,), self.bias_init, dtype),   # visible
        }

    # -- conditionals -------------------------------------------------------

    def _hidden_mean(self, params, v):
        pre = v @ params["W"] + params["b"]
        if self.hidden_unit == "RECTIFIED":
            return jnp.maximum(pre, 0.0)
        return jax.nn.sigmoid(pre)

    def _sample_hidden(self, params, v, key):
        pre = v @ params["W"] + params["b"]
        if self.hidden_unit == "RECTIFIED":
            # NReLU sampling: max(0, pre + N(0, sigmoid(pre))) (reference
            # RBM.java RECTIFIED branch uses pre + gaussian noise)
            noise = jax.random.normal(key, pre.shape, pre.dtype)
            return jnp.maximum(
                0.0, pre + noise * jnp.sqrt(jax.nn.sigmoid(pre))
            )
        p = jax.nn.sigmoid(pre)
        return jax.random.bernoulli(key, p).astype(pre.dtype)

    def _visible_mean(self, params, h):
        pre = h @ params["W"].T + params["vb"]
        if self.visible_unit == "GAUSSIAN":
            return pre
        return jax.nn.sigmoid(pre)

    def _sample_visible(self, params, h, key):
        mean = self._visible_mean(params, h)
        if self.visible_unit == "GAUSSIAN":
            return mean + jax.random.normal(key, mean.shape, mean.dtype)
        return jax.random.bernoulli(key, mean).astype(mean.dtype)

    def free_energy(self, params, v):
        """F(v) for monitoring; binary hidden only: F = -v·vb -
        Σ softplus(vW + b), Gaussian visible adds 0.5‖v−vb‖²."""
        if self.hidden_unit != "BINARY":
            raise ValueError(
                "free_energy has a closed form only for BINARY hidden "
                f"units (got {self.hidden_unit})"
            )
        pre_h = v @ params["W"] + params["b"]
        hidden_term = jnp.sum(jax.nn.softplus(pre_h), axis=-1)
        if self.visible_unit == "GAUSSIAN":
            vis_term = 0.5 * jnp.sum((v - params["vb"]) ** 2, axis=-1)
            return vis_term - hidden_term
        return -(v @ params["vb"]) - hidden_term

    def _pseudo_energy(self, params, v):
        """Energy with hidden statistics held constant
        (stop-gradient): its gradient wrt (W, b, vb) is exactly the
        per-phase CD statistic — -v^T·E[h|v], -E[h|v], -v — for
        WHATEVER hidden mean the unit type defines (sigmoid for
        BINARY, max(0,·) for RECTIFIED), matching the reference's CD
        update which uses the unit's own conditional mean."""
        h = lax.stop_gradient(self._hidden_mean(params, v))
        pre_h = v @ params["W"] + params["b"]
        hidden_term = jnp.sum(h * pre_h, axis=-1)
        if self.visible_unit == "GAUSSIAN":
            vis_term = 0.5 * jnp.sum((v - params["vb"]) ** 2, axis=-1)
            return vis_term - hidden_term
        return -(v @ params["vb"]) - hidden_term

    # -- supervised forward: propUp -----------------------------------------

    def supports_drop_connect(self) -> bool:
        return True

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        params = self.maybe_drop_connect(params, train=train, rng=rng)
        return self._hidden_mean(params, x), state

    # -- CD-k ---------------------------------------------------------------

    def gibbs_chain(self, params, v0, rng):
        """k alternating Gibbs steps from v0; returns the negative-phase
        visible sample (chain end)."""
        def body(i, carry):
            v, key = carry
            key, kh, kv = jax.random.split(key, 3)
            h = self._sample_hidden(params, v, kh)
            v = self._sample_visible(params, h, kv)
            return (v, key)

        v_neg, _ = lax.fori_loop(0, self.k, body, (v0, rng))
        return v_neg

    def pretrain_loss(self, params, x, rng):
        if rng is None:
            rng = jax.random.PRNGKey(0)
        v_neg = lax.stop_gradient(self.gibbs_chain(params, x, rng))
        cd = jnp.mean(
            self._pseudo_energy(params, x) - self._pseudo_energy(params, v_neg)
        )
        # Monitor term with zero gradient: reconstruction error, the
        # quantity the reference reports as the RBM score.
        recon = self._visible_mean(params, self._hidden_mean(params, x))
        err = jnp.mean(jnp.sum((lax.stop_gradient(recon) - x) ** 2, axis=-1))
        return cd + lax.stop_gradient(err - cd)

    def reconstruction_error(self, params, x):
        recon = self._visible_mean(params, self._hidden_mean(params, x))
        return jnp.mean(jnp.sum((recon - x) ** 2, axis=-1))
