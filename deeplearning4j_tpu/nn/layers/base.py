"""Layer SPI and registry.

The reference splits each layer into a config bean
(``nn/conf/layers/*.java``), a ``ParamInitializer``
(``nn/params/*.java``) and a runtime impl (``nn/layers/**``) with
hand-written ``activate``/``backpropGradient`` pairs. In a functional
JAX design those collapse into one class per layer: a frozen dataclass
that is simultaneously the JSON-serializable config and the pure
``init_params``/``apply`` implementation. Backprop is ``jax.grad``
through ``apply`` — there is no second code path to keep consistent
(the reference's gradient checks validated exactly that consistency;
ours validate the whole jitted composition instead).

Contract:
- ``init_params(key, dtype) -> {name: array}`` named like the
  reference's param keys ("W", "b", "gamma", ...): checkpoints stay
  humanly mappable to the reference's flat-view layout.
- ``apply(params, x, state, *, train, rng) -> (y, state)`` — ``state``
  carries non-trainable buffers (batch-norm running stats); stateless
  layers pass {} through.
- ``output_type(input)`` / ``with_input_type(input)`` implement the
  reference's InputType shape inference (``setNIn``).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Type

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.updaters import UpdaterSettings
from deeplearning4j_tpu.nn.weights import Distribution, init_weights

# JSON subtype registry (reference: Jackson subtype scan,
# ``NeuralNetConfiguration.java:328-462``; here an explicit registry —
# custom layers call ``register_layer`` instead of being discovered by
# classpath scan).
LAYER_REGISTRY: Dict[str, Type["LayerSpec"]] = {}


def register_layer(cls):
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_to_json(layer: "LayerSpec") -> dict:
    d = {"@class": type(layer).__name__}
    for f in dataclasses.fields(layer):
        v = getattr(layer, f.name)
        if isinstance(v, Distribution):
            v = {"@dist": True, **v.to_json()}
        elif isinstance(v, InputType):
            v = {"@input_type": True, **v.to_json()}
        elif isinstance(v, LayerSpec):
            v = layer_to_json(v)
        elif hasattr(v, "to_json") and hasattr(v, "neg_log_prob"):
            v = v.to_json()  # ReconstructionDistribution (tagged @dist_class)
        elif isinstance(v, tuple):
            v = list(v)
        d[f.name] = v
    return d


def layer_from_json(d: dict) -> "LayerSpec":
    d = dict(d)
    name = d.pop("@class")
    try:
        cls = LAYER_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"Unknown layer type '{name}' — register custom layers with "
            f"@register_layer before deserializing"
        ) from None
    kwargs = {}
    field_types = {f.name: f for f in dataclasses.fields(cls)}
    for k, v in d.items():
        if k not in field_types:
            continue  # forward compat: ignore unknown fields
        if isinstance(v, dict) and v.get("@dist"):
            v = Distribution.from_json({
                kk: vv for kk, vv in v.items() if kk != "@dist"
            })
        elif isinstance(v, dict) and v.get("@input_type"):
            v = InputType.from_json({
                kk: vv for kk, vv in v.items() if kk != "@input_type"
            })
        elif isinstance(v, dict) and "@dist_class" in v:
            from deeplearning4j_tpu.nn.layers.variational import (
                ReconstructionDistribution,
            )

            v = ReconstructionDistribution.from_json(v)
        elif isinstance(v, dict) and "@class" in v:
            v = layer_from_json(v)
        elif isinstance(v, list):
            v = tuple(
                layer_from_json(x) if isinstance(x, dict) and "@class" in x else x
                for x in v
            )
        kwargs[k] = v
    return cls(**kwargs)


@dataclass(frozen=True)
class LayerSpec:
    """Base config+impl for all layers (reference
    ``nn/conf/layers/Layer.java`` bean fields)."""

    name: str = ""
    activation: str = "sigmoid"
    weight_init: str = "XAVIER"
    dist: Distribution | None = None
    bias_init: float = 0.0
    dropout: float = 0.0
    # weight-level DropConnect (reference NeuralNetConfiguration
    # ``useDropConnect``, NeuralNetConfiguration.java:96,509): when
    # True the ``dropout`` rate masks WEIGHTS in pre-output instead of
    # masking the layer input (BaseLayer.java:365,480)
    drop_connect: bool = False
    # optimizer settings (per-layer overrides; reference clones the
    # global NeuralNetConfiguration per layer)
    updater: str = "SGD"
    learning_rate: float = 0.1
    bias_learning_rate: float | None = None
    momentum: float = 0.9
    adam_mean_decay: float = 0.9
    adam_var_decay: float = 0.999
    rho: float = 0.95
    rms_decay: float = 0.95
    epsilon: float = 1e-8
    l1: float = 0.0
    l2: float = 0.0
    gradient_normalization: str = "None"
    gradient_normalization_threshold: float = 1.0
    lr_policy: str = "None"
    lr_policy_decay_rate: float = 0.0
    lr_policy_steps: float = 1.0
    lr_policy_power: float = 1.0
    lr_schedule: dict | None = None

    # -- shape inference ---------------------------------------------------

    def with_input_type(self, input_type: InputType) -> "LayerSpec":
        """Return a copy with nIn etc. inferred (reference
        ``Layer.setNIn``); default: unchanged."""
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    # -- params / state ----------------------------------------------------

    def init_params(self, key: jax.Array, dtype=jnp.float32) -> dict:
        return {}

    def init_state(self, dtype=jnp.float32) -> dict:
        return {}

    def regularizable_params(self) -> tuple:
        return ("W",)

    # -- forward -----------------------------------------------------------

    def apply(self, params, x, state, *, train: bool = False, rng=None,
              mask=None):
        """``mask``: optional [batch, time] features mask, consumed by
        recurrent layers; others ignore it."""
        raise NotImplementedError

    def is_recurrent(self) -> bool:
        """True for layers with streaming/TBPTT carry state (reference
        ``RecurrentLayer`` interface)."""
        return False

    def can_stream(self) -> bool:
        """False for layers that need the whole sequence (bidirectional
        RNNs) and therefore cannot be used with rnn_time_step."""
        return True

    def uses_batch_statistics(self) -> bool:
        """True for layers whose TRAINING math couples examples across
        the batch (BatchNormalization): under data parallelism these
        decide sync-vs-local batch stats (see
        ``parallel.trainer.DistributedTrainer``)."""
        return False

    def streams_state(self) -> bool:
        """True for layers that carry state across ``rnn_time_step``
        calls: recurrent layers (h/c) and attention layers (KV cache).
        Distinct from ``is_recurrent`` — attention layers stream at
        inference but train with whole-sequence scan fusion."""
        return self.is_recurrent()

    def stream_state_keys(self) -> tuple:
        """State-dict keys ``rnn_time_step`` carries across calls."""
        return ("h", "c")

    def stream_capacity(self):
        """Max total timesteps this layer can stream (None =
        unbounded; recurrent carry is O(1)). KV caches are finite."""
        return None

    # -- helpers -----------------------------------------------------------

    def activate_fn(self):
        return activations.get(self.activation)

    def supports_drop_connect(self) -> bool:
        """True for layers whose ``apply`` routes weights through
        :meth:`maybe_drop_connect` (dense/conv/LSTM/pretrain families,
        mirroring the reference's BaseLayer/ConvolutionLayer/
        LSTMHelpers DropConnect sites). Layers without weight-level
        masking keep their INPUT dropout even when the global
        ``drop_connect`` flag is set — otherwise the flag would
        silently strip their only regularization."""
        return False

    def maybe_dropout(self, x, *, train: bool, rng):
        """Inverted dropout on the layer *input* (reference BaseLayer
        applies dropout to input when training, ``conf.dropOut``).
        Suppressed when ``drop_connect`` is set AND this layer
        implements weight masking — the reference routes the rate to
        the weights instead (BaseLayer.java:480 checks
        ``!conf.isUseDropConnect()``)."""
        if (not train or self.dropout <= 0.0 or rng is None
                or (self.drop_connect and self.supports_drop_connect())):
            return x
        keep = 1.0 - self.dropout
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)

    # distinct stream from input dropout so a hypothetical layer using
    # both would not correlate masks
    _DROP_CONNECT_SALT = 0x7C

    def maybe_drop_connect(self, params, *, train: bool, rng,
                           keys=("W",)):
        """DropConnect: return ``params`` with the weight tensors in
        ``keys`` masked at rate ``dropout`` (reference
        ``Dropout.applyDropConnect``, applied by BaseLayer.java:365,
        ConvolutionLayer.java:223 and LSTMHelpers.java:93 to the
        input-weight matrices). Inverted scaling (W/keep) keeps
        pre-activation expectations unchanged, matching this
        framework's input-dropout convention. Deterministic in ``rng``
        so the engine's separate pre-output call sees the same mask as
        ``apply``."""
        if (not train or not self.drop_connect or self.dropout <= 0.0
                or rng is None or not self.supports_drop_connect()):
            return params
        keep = 1.0 - self.dropout
        out = dict(params)
        for i, k in enumerate(keys):
            if k not in out:
                continue
            w = out[k]
            m = jax.random.bernoulli(
                jax.random.fold_in(rng, self._DROP_CONNECT_SALT + i),
                keep, w.shape,
            )
            out[k] = jnp.where(m, w / keep, 0.0)
        return out

    def supports_layer_scan(self) -> bool:
        """True when this layer may join a scan-over-layers run
        (``nn/core.py``): its per-step program must be self-contained —
        no recurrent/TBPTT carry, no loss head, no pretrain phase, no
        cross-example batch statistics. Layers with non-empty
        ``init_state`` are additionally excluded at detection time
        (their state would have to thread through the scan carry)."""
        return not (
            self.is_recurrent()
            or self.has_loss()
            or self.is_pretrainable()
            or self.uses_batch_statistics()
        )

    def updater_settings(self) -> UpdaterSettings:
        return UpdaterSettings(
            updater=self.updater,
            learning_rate=self.learning_rate,
            bias_learning_rate=self.bias_learning_rate,
            momentum=self.momentum,
            adam_mean_decay=self.adam_mean_decay,
            adam_var_decay=self.adam_var_decay,
            rho=self.rho,
            rms_decay=self.rms_decay,
            epsilon=self.epsilon,
            l1=self.l1,
            l2=self.l2,
            gradient_normalization=self.gradient_normalization,
            gradient_normalization_threshold=self.gradient_normalization_threshold,
            lr_policy=self.lr_policy,
            lr_policy_decay_rate=self.lr_policy_decay_rate,
            lr_policy_steps=self.lr_policy_steps,
            lr_policy_power=self.lr_policy_power,
            lr_schedule=self.lr_schedule,
            regularizable=self.regularizable_params(),
        )

    # -- pretraining hook --------------------------------------------------

    def is_pretrainable(self) -> bool:
        return False

    def has_loss(self) -> bool:
        return False

    def input_kind(self) -> str:
        """Data family this layer consumes: feedforward | convolutional
        | recurrent | any. Drives auto-preprocessor insertion."""
        return "feedforward"


@dataclass(frozen=True)
class FeedForwardLayerSpec(LayerSpec):
    """Base for layers with nIn/nOut (reference
    ``nn/conf/layers/FeedForwardLayer.java``)."""

    n_in: int = 0
    n_out: int = 0

    def with_input_type(self, input_type: InputType) -> "FeedForwardLayerSpec":
        if self.n_in == 0:
            return dataclasses.replace(self, n_in=input_type.flat_size())
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)
