"""ComputationGraph — the DAG engine (reference:
``nn/graph/ComputationGraph.java``, 4.1k LoC; forward = topo-ordered
``doForward`` per vertex, backward = reverse topo ``doBackward``).

TPU-first: the topo walk happens at *trace* time — the whole DAG
(all vertices, multi-input fan-in, multi-output losses) flattens into
one XLA program per input shape, and the reverse-order backward pass
is ``jax.grad`` of that program. Multi-output losses sum (reference
sums output-layer scores).

Like ``MultiLayerNetwork``, this engine is a wrapper over the unified
functional core (``nn/core.py``): the jitted step builders, scan-fused
multi-step, pretrain step, fit drivers, and whole-net transforms
(scan-over-layers on linear vertex chains, activation remat, dynamic
loss scaling) are implemented there once — only the DAG walk itself is
engine-specific (``scripts/lint_parity.py`` enforces the split). The
core also brings the divergence guard and step telemetry to this
engine, which previously only the sequential engine wired in.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import core
from deeplearning4j_tpu.observability import profiler
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ComputationGraphConfiguration,
    DuplicateToTimeSeriesVertex,
    LastTimeStepVertex,
    LayerVertex,
)
from deeplearning4j_tpu.nn.updaters import MultiLayerUpdaterDef, UpdaterSettings


def _as_list(x) -> list:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.topo: List[str] = conf.topological_order()
        self.layer_vertex_names: List[str] = [
            n for n in self.topo
            if isinstance(conf.vertices[n], LayerVertex)
        ]
        settings: Dict[str, UpdaterSettings] = {}
        for n in self.layer_vertex_names:
            settings[n] = conf.vertices[n].layer_conf.updater_settings()
        self.updater_def = MultiLayerUpdaterDef(settings)
        self.params: Optional[dict] = None
        self.state: Dict[str, dict] = {}
        self.updater_state = None
        self.iteration_count = 0
        self.epoch_count = 0
        self._last_score = float("nan")
        self.listeners: List[Any] = []
        self._jit_step = None
        self._jit_multi_step = None
        self._solver = None  # lazily built for LBFGS/CG/line-search
        self.scan_chunk = 16  # minibatches fused per dispatch
        # multi-epoch fits keep the dataset HBM-resident up to this
        # size, derived from the device's reported memory limit
        from deeplearning4j_tpu.util.device import device_cache_budget_bytes

        self.device_cache_bytes = device_cache_budget_bytes()
        self._jit_output = None
        # AOT-restored inference executables by exact input-shape key
        # (compile/aot.py): consulted by output() before the jit path
        self._aot_outputs: Dict[tuple, Any] = {}
        self._jit_rnn_step = None
        self._rnn_state: Dict[str, Any] = {}  # streaming rnnTimeStep
        self._stream_steps = 0  # timesteps consumed vs finite caches
        self._jit_pretrain_steps: Dict[str, Any] = {}
        self._jit_pretrain_inputs: Dict[str, Any] = {}
        # device-resident scan constants (see core.scan_consts)
        self._scan_const_cache: Dict[Any, Any] = {}
        self._it0_dev = None
        self._it0_shadow = -1
        self._pretrain_done = False
        self._base_key = jax.random.PRNGKey(conf.seed)
        # resilience.DivergenceGuard — wired through the core step
        # builder exactly like MultiLayerNetwork (in-jit suppression,
        # host-side skip/rollback policy)
        self.divergence_guard = None
        # observability step telemetry (in-jit grad global norm)
        self._telemetry_grad_norm = False
        self._last_grad_norm = None
        # async dispatch knobs (core.fit_batches runs the per-step
        # loop through an AsyncDispatchWindow)
        self.max_in_flight = 2
        self.guard_lag = None
        self._dispatch_window = None
        self._last_batch_rows = None  # host int; examples/sec signal
        # whole-net transform knobs — see core.set_transforms
        core.init_transforms(self, conf)

    @property
    def score_value(self) -> float:
        """Latest minibatch score (reading syncs with the device)."""
        return float(self._last_score)

    @score_value.setter
    def score_value(self, v) -> None:
        self._last_score = v

    def _dtype(self):
        return jnp.dtype(self.conf.dtype)

    # ------------------------------------------------------------------

    def init(self, params: Optional[dict] = None) -> "ComputationGraph":
        dtype = self._dtype()
        conf = self.conf
        if params is not None:
            # checkpoint npz round-trips drop empty entries; param-less
            # layer vertices get their {} slot back, but a missing
            # PARAMETERIZED vertex is checkpoint corruption — fail here
            restored = {}
            for n in self.layer_vertex_names:
                if n in params:
                    restored[n] = params[n]
                elif conf.vertices[n].init_params(self._base_key, dtype):
                    raise ValueError(
                        f"checkpoint has no params for vertex '{n}'"
                    )
                else:
                    restored[n] = {}
            self.params = restored
        else:
            keys = jax.random.split(
                self._base_key, max(len(self.layer_vertex_names), 1)
            )
            self.params = {
                n: conf.vertices[n].init_params(k, dtype)
                for n, k in zip(self.layer_vertex_names, keys)
            }
        self.state = {
            n: conf.vertices[n].init_state(dtype)
            for n in self.layer_vertex_names
        }
        self.updater_state = self.updater_def.init(self.params)
        self._pretrain_done = False  # fresh params => pretrain again
        return self

    # ------------------------------------------------------------------
    # whole-net transforms (implemented once in nn/core.py)
    # ------------------------------------------------------------------

    def set_transforms(self, scan_layers=None, remat=None,
                       loss_scale=None,
                       megastep=None) -> "ComputationGraph":
        """(Re)configure the whole-net transforms — same contract as
        ``MultiLayerNetwork.set_transforms``. ``scan_layers`` here
        scans LINEAR CHAINS of identical layer vertices (consecutive
        topo positions, single consumer each); ``megastep=K`` folds K
        optimizer steps into one dispatch."""
        core.set_transforms(self, scan_layers, remat, loss_scale,
                            megastep)
        return self

    @property
    def _loss_scale_active(self) -> bool:
        return core.loss_scale_active(self)

    def _active_vertex_chains(self) -> tuple:
        if self._layer_runs_cache is None:
            self._layer_runs_cache = tuple(core.detect_vertex_chains(
                self.conf, self.topo
            ))
        return self._layer_runs_cache

    def scan_layer_run_count(self) -> int:
        """Active scanned vertex chains (telemetry signal)."""
        return (
            len(self._active_vertex_chains()) if self.scan_layers else 0
        )

    def set_divergence_guard(self, guard) -> None:
        """(Un)install a resilience.DivergenceGuard on the train step
        (in-jit NaN/Inf suppression + host-side skip/rollback; with
        ``guard.stats`` also the statistical anomaly guard) — the
        core step builder gives the DAG engine the same machinery as
        the sequential engine."""
        self.divergence_guard = guard
        self._jit_step = None
        self._jit_megastep = None

    def set_batch_validator(self, validator, quarantine=None
                            ) -> "ComputationGraph":
        """(Un)install the data-plane defense (``datasets.validate``)
        on this model's ``fit`` loops."""
        core.set_batch_validator(self, validator, quarantine)
        return self

    def enable_step_telemetry(self, enabled: bool = True) -> None:
        """(Un)install step telemetry: the jitted step additionally
        returns the gradient global L2 norm (one fused scalar)."""
        if enabled != self._telemetry_grad_norm:
            self._telemetry_grad_norm = enabled
            self._jit_step = None
            self._jit_megastep = None

    # ------------------------------------------------------------------

    def _forward_values(self, params, state, inputs: Sequence, *,
                        train: bool, rng, fmasks=None,
                        use_scan: bool = False):
        """Walk the topo order; returns ({vertex: value}, preouts,
        new_state). ``fmasks``: per-graph-input [b, t] masks.
        ``use_scan=True`` (score/output paths, which only read the
        output vertices) lets detected linear chains of identical
        layer vertices run under one ``lax.scan`` — their inner
        values are then not materialized, so callers that need every
        vertex's activation (``feed_forward``) keep it off."""
        conf = self.conf
        cdt = core.compute_dtype_of(conf)
        if cdt != self._dtype():
            # mixed precision (same contract as MultiLayerNetwork):
            # master params keep the storage dtype, compute runs in cdt
            params = core.cast_floats(params, cdt)
            inputs = [core.cast_floats(x, cdt) for x in inputs]
            if fmasks is not None:
                fmasks = [
                    None if m is None else core.cast_floats(m, cdt)
                    for m in fmasks
                ]
        # engine-global shape context for preprocessors: batch/time of
        # the ORIGINAL minibatch (vertex-local inputs may be flattened)
        from deeplearning4j_tpu.nn.conf.preprocessors import ShapeContext

        g_time = max(
            (int(x.shape[2]) for x in inputs if x.ndim == 3), default=-1
        )
        gctx = ShapeContext(
            batch=int(inputs[0].shape[0]) if inputs else 0, time=g_time
        )
        values: Dict[str, Any] = dict(zip(conf.inputs, inputs))
        masks: Dict[str, Any] = {}
        if fmasks is not None:
            masks = {
                name: m for name, m in zip(conf.inputs, fmasks)
                if m is not None
            }
        new_state = dict(state)
        preouts: Dict[str, Any] = {}
        # Per-input masks follow the DAG: each vertex sees the mask
        # propagated from whichever graph input feeds its branch
        # (reference feedForwardMaskArrays). Time-collapsing vertices
        # (LastTimeStep) clear the mask downstream.
        vmask: Dict[str, Any] = dict(masks)
        chain_at = (
            {s: e for s, e in self._active_vertex_chains()}
            if (use_scan and self.scan_layers) else {}
        )
        rem = self.remat if train else "none"
        i, n_topo = 0, len(self.topo)
        while i < n_topo:
            name = self.topo[i]
            v = conf.vertices[name]
            end = chain_at.get(i)
            if end is not None:
                names = self.topo[i:end]
                if core.run_is_ready(names, params, state):
                    # scan-over-layers on a linear vertex chain: the
                    # per-vertex rng indices are the topo positions,
                    # bitwise-matching the unrolled walk
                    src = conf.vertex_inputs[name][0]
                    x = values[src]
                    mask = vmask.get(src)
                    out = core.apply_layer_run(
                        v.layer_conf, names, params, x, train=train,
                        rng=rng, idx0=i, mask=mask, remat=rem,
                    )
                    last = names[-1]
                    values[last] = out
                    vmask[last] = mask
                    for cn in names:
                        new_state[cn] = state.get(cn, {})
                    i = end
                    continue
            vin = [values[s] for s in conf.vertex_inputs[name]]
            in_masks = [
                vmask.get(s) for s in conf.vertex_inputs[name]
            ]
            mask = next((m for m in in_masks if m is not None), None)
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            vparams = params.get(name, {}) if isinstance(v, LayerVertex) else {}
            vstate = state.get(name, {})
            if isinstance(v, DuplicateToTimeSeriesVertex):
                ref = values[v.reference_input]
                out, st = v.apply(
                    vparams, vin, vstate, train=train, rng=lrng,
                    time=ref.shape[2],
                )
                vmask[name] = vmask.get(v.reference_input)
            elif isinstance(v, LastTimeStepVertex):
                m = masks.get(v.mask_input) if v.mask_input else mask
                out, st = v.apply(vparams, vin, vstate, train=train,
                                  rng=lrng, mask=m)
                vmask[name] = None  # time axis collapsed
            elif isinstance(v, LayerVertex):
                def apply_vertex(p, xs, st, *, _v=v, _rng=lrng,
                                 _mask=mask):
                    return _v.apply(p, xs, st, train=train, rng=_rng,
                                    mask=_mask, ctx=gctx)

                if rem != "none" and not v.layer_conf.has_loss():
                    # activation remat per vertex (jax.checkpoint):
                    # the backward pass recomputes this vertex's
                    # forward instead of keeping its activations
                    apply_vertex = core.maybe_remat(apply_vertex, rem)
                out, st = apply_vertex(vparams, vin, vstate)
                vmask[name] = mask
            else:
                out, st = v.apply(vparams, vin, vstate, train=train,
                                  rng=lrng, mask=mask)
                vmask[name] = mask
            if isinstance(v, LayerVertex):
                new_state[name] = st
                layer = v.layer_conf
                if name in conf.outputs and layer.has_loss():
                    x = vin[0]
                    if v.preprocessor is not None:
                        x = v.preprocessor.preprocess(x, gctx)
                    x = layer.maybe_dropout(x, train=train, rng=lrng)
                    # same lrng as apply -> identical DropConnect mask
                    pw = layer.maybe_drop_connect(
                        params[name], train=train, rng=lrng
                    )
                    preouts[name] = layer.pre_output(pw, x)
            values[name] = out
            i += 1
        return values, preouts, new_state

    def _score_pure(self, params, state, inputs, labels, lmasks, rng, *,
                    train: bool, fmasks=None):
        from deeplearning4j_tpu.nn import losses as losses_mod

        values, preouts, new_state = self._forward_values(
            params, state, inputs, train=train, rng=rng, fmasks=fmasks,
            use_scan=True,
        )
        score = 0.0
        for i, out_name in enumerate(self.conf.outputs):
            v = self.conf.vertices[out_name]
            layer = v.layer_conf if isinstance(v, LayerVertex) else None
            if layer is None or not layer.has_loss():
                raise ValueError(
                    f"Output vertex '{out_name}' has no loss function"
                )
            y = labels[i]
            m = lmasks[i] if lmasks is not None else None
            score = score + losses_mod.score(
                layer.loss, y, preouts[out_name], layer.activation, m, True
            )
        reg = 0.0
        for n in self.layer_vertex_names:
            layer = self.conf.vertices[n].layer_conf
            reg = reg + core.reg_penalty(layer, params[n])
        return score + reg, new_state

    # ------------------------------------------------------------------
    # jitted train step (built by the core)
    # ------------------------------------------------------------------

    def _score_fn(self):
        """The engine's contribution to the core step builders (the
        labels-mask slot carries this engine's per-output lmasks
        list, the features-mask slot its per-input fmasks list)."""
        def score_fn(p, state, inputs, labels, lmasks, fmasks, rng):
            return self._score_pure(
                p, state, inputs, labels, lmasks, rng, train=True,
                fmasks=fmasks,
            )
        return score_fn

    def _recurrent_names(self):
        return [
            n for n in self.layer_vertex_names
            if self.conf.vertices[n].layer_conf.is_recurrent()
        ]

    def _build_step(self):
        return core.build_step(
            self._score_fn(), self.updater_def,
            guarded=self.divergence_guard is not None,
            telemetry=self._telemetry_grad_norm,
            loss_scale=self._loss_scale_active,
            grad_accum=self.grad_accum,
            recurrent_names=self._recurrent_names(),
            zero_layout=self._zero_layout,
            stat_guard=core.stat_guard_config(self),
        )

    def _multi_cast(self):
        multi_dtype = self._dtype()

        def cast(x, labels, mask, fmask):
            c = lambda v: (  # noqa: E731 — cast-on-device contract
                None if v is None
                else [None if a is None else a.astype(multi_dtype)
                      for a in v]
            )
            return c(x), c(labels), c(mask), c(fmask)
        return cast

    def _build_multi_step(self):
        return core.build_multi_step(
            self._score_fn(), self.updater_def,
            cast=self._multi_cast(),
            recurrent_names=self._recurrent_names(),
            grad_accum=self.grad_accum,
            zero_layout=self._zero_layout,
        )

    def _build_megastep(self):
        """K full train steps fused into one dispatch, full step
        flavor (core.build_megastep) — same contract as the
        sequential engine's."""
        return core.build_megastep(
            self._score_fn(), self.updater_def,
            cast=self._multi_cast(),
            recurrent_names=self._recurrent_names(),
            guarded=self.divergence_guard is not None,
            telemetry=self._telemetry_grad_norm,
            loss_scale=self._loss_scale_active,
            stat_guard=core.stat_guard_config(self),
            grad_accum=self.grad_accum,
            zero_layout=self._zero_layout,
        )

    def _can_scan_steps(self) -> bool:
        return (
            self.conf.iterations == 1
            and self.conf.backprop_type != "TruncatedBPTT"
            and getattr(
                self.conf, "optimization_algo",
                "STOCHASTIC_GRADIENT_DESCENT",
            ) == "STOCHASTIC_GRADIENT_DESCENT"
            and self.divergence_guard is None
            and not self._loss_scale_active
            and not any(
                self.conf.vertices[n].layer_conf.is_recurrent()
                for n in self.layer_vertex_names
            )
            and all(
                getattr(l, "supports_batched_iterations", False)
                for l in self.listeners
            )
        )

    def _ds_scan_sig(self, ds) -> tuple:
        def sh(v):
            # np.shape, NOT np.asarray(a).shape — asarray would pull
            # device arrays to host per batch (see core.py)
            return tuple(
                None if a is None else tuple(np.shape(a))
                for a in v
            ) if v else None
        f, l, fm, lm = self._ds_arrays(ds)
        return (sh(f), sh(l), sh(fm or []), sh(lm or []))

    def _ds_arrays(self, ds):
        features = _as_list(getattr(ds, "features"))
        labels = _as_list(getattr(ds, "labels"))
        fmasks = _as_list(getattr(ds, "features_masks", None)
                          or getattr(ds, "features_mask", None))
        lmasks = _as_list(getattr(ds, "labels_masks", None)
                          or getattr(ds, "labels_mask", None))
        return features, labels, fmasks or None, lmasks or None

    def _stack_chunk(self, batches: list):
        """Stack k same-shaped minibatches into device-resident lists
        ``(x, y, labels_masks, features_masks, k)`` — the uniform
        stacked-chunk layout core.run_scan_chunk drives (integer
        inputs keep native width; already-device arrays stack ON
        DEVICE — no host round trip)."""
        dtype = self._dtype()
        rows = [self._ds_arrays(b) for b in batches]

        def stack_lists(idx):
            first = rows[0][idx]
            if first is None:
                return None
            return [
                None if first[j] is None
                else core.stack_on_device(
                    [r[idx][j] for r in rows], dtype
                )
                for j in range(len(first))
            ]

        return (
            stack_lists(0), stack_lists(1), stack_lists(3),
            stack_lists(2), len(batches),
        )

    def _prep_prestacked(self, ds):
        """Single-input [k, b, ...] chunk payload -> this engine's
        stacked device 5-tuple (per-slot lists; same dtype contract
        as stack_on_device)."""
        dtype = self._dtype()

        def prep(a):
            if a is None:
                return None
            a = a if isinstance(a, jax.Array) else jnp.asarray(a)
            return core.cast_stacked(a, dtype)

        lm = getattr(ds, "labels_mask", None)
        fm = getattr(ds, "features_mask", None)
        return (
            [prep(ds.features)], [prep(ds.labels)],
            None if lm is None else [prep(lm)],
            None if fm is None else [prep(fm)],
            ds.k,
        )

    def _run_prestacked_chunk(self, ds) -> None:
        """One fused dispatch from a single-input ChunkedDataSet's
        [k, b, ...] arrays."""
        if ds.k == 1:
            self.fit_minibatch(ds)  # fit_minibatch unstacks
            return
        core.run_scan_chunk(self, self._prep_prestacked(ds))

    # ------------------------------------------------------------------

    def fit(self, data, labels=None, *, epochs: int = 1,
            grad_accum=None, megastep=None) -> None:
        """Accepts a MultiDataSet/DataSet, an iterator of either, or
        (inputs, labels) lists (reference fit overloads
        ``ComputationGraph.java:614-760``). ``grad_accum=K``
        accumulates K microbatch gradients in-jit per optimizer step;
        ``megastep=K`` folds K optimizer steps into one dispatch
        (same contracts as ``MultiLayerNetwork.fit``)."""
        if megastep is not None:
            self.set_transforms(megastep=megastep)
        if grad_accum is not None:
            if (
                int(grad_accum) > 1
                and self.conf.backprop_type == "TruncatedBPTT"
            ):
                raise ValueError(
                    "grad_accum > 1 is incompatible with TBPTT: the "
                    "recurrent carry threads between chunks, so a "
                    "chunk cannot split into independent microbatches"
                )
            core.set_grad_accum(self, grad_accum)
        if labels is not None:
            from deeplearning4j_tpu.datasets.api import MultiDataSet

            mds = MultiDataSet(features=_as_list(data),
                               labels=_as_list(labels))
            core.fit_batches(self, [mds], epochs)
            return
        if hasattr(data, "features"):
            core.fit_batches(self, [data], epochs)
            return
        core.fit_batches(self, data, epochs)

    def _fit_epochs_device_cached(self, iterator, epochs: int) -> bool:
        def arrays_of(ds):
            for group in self._ds_arrays(ds):
                yield from group or []

        return core.fit_epochs_device_cached(
            self, iterator, epochs, arrays_of
        )

    def pretrain(self, data, epochs: int = 1) -> None:
        """Greedy layer-wise unsupervised pretraining of every
        pretrainable layer vertex (VAE/RBM/AutoEncoder), in topological
        order, each on the activations the frozen graph feeds it
        (reference ``ComputationGraph.pretrain``,
        ``ComputationGraph.java:509``)."""
        if self.params is None:
            self.init()
        if hasattr(data, "features"):
            data = [data]
        elif not isinstance(data, (list, tuple)) and not hasattr(
            data, "reset"
        ):
            data = list(data)
        dtype = self._dtype()
        for topo_idx, n in enumerate(self.topo):
            v = self.conf.vertices.get(n)
            if not isinstance(v, LayerVertex):
                continue
            layer = v.layer_conf
            if not layer.is_pretrainable():
                continue
            upd_def = MultiLayerUpdaterDef({n: layer.updater_settings()})
            upd_state = upd_def.init({n: self.params[n]})
            if n not in self._jit_pretrain_steps:
                def make_input(n=n, v=v):
                    from deeplearning4j_tpu.nn.conf.preprocessors import (
                        ShapeContext,
                    )

                    def input_fn(params, state, inputs):
                        values, _, _ = self._forward_values(
                            params, state, inputs, train=False, rng=None
                        )
                        x = values[self.conf.vertex_inputs[n][0]]
                        if v.preprocessor is not None:
                            t = x.shape[2] if x.ndim == 3 else -1
                            x = v.preprocessor.preprocess(
                                x, ShapeContext(batch=x.shape[0], time=t)
                            )
                        return x

                    return jax.jit(input_fn)

                self._jit_pretrain_steps[n] = core.build_pretrain_step(
                    layer, n, upd_def
                )
                self._jit_pretrain_inputs[n] = make_input()
            step = self._jit_pretrain_steps[n]
            jit_input = self._jit_pretrain_inputs[n]
            it = 0
            # the frozen lower graph never changes while vertex n
            # trains: for materialized data, compute each batch's input
            # activation once and reuse it across all epochs — bounded
            # by device_cache_bytes like every other caching path
            xin_cache = None
            if isinstance(data, (list, tuple)):
                xin_cache = []
                cached_bytes = 0
                for ds in data:
                    xin = jit_input(self.params, self.state, [
                        jnp.asarray(f, dtype)
                        for f in _as_list(ds.features)
                    ])
                    cached_bytes += core.nbytes(xin)
                    if cached_bytes > self.device_cache_bytes:
                        xin_cache = None  # too big: recompute per epoch
                        break
                    xin_cache.append(xin)
            for _ in range(epochs):
                batches = (
                    xin_cache if xin_cache is not None else (
                        jit_input(self.params, self.state, [
                            jnp.asarray(f, dtype)
                            for f in _as_list(ds.features)
                        ])
                        for ds in data
                    )
                )
                for xin in batches:
                    for _ in range(self.conf.iterations):
                        lrs = {
                            k: jnp.asarray(val, jnp.float32)
                            for k, val in upd_def.scheduled_lrs(it).items()
                        }
                        t = jnp.asarray(it + 1, jnp.float32)
                        rng = jax.random.fold_in(
                            jax.random.fold_in(
                                self._base_key, 7919 + topo_idx
                            ),
                            it,
                        )
                        (
                            self.params[n], upd_state, loss,
                        ) = step(
                            self.params[n], upd_state, xin, lrs, t, rng
                        )
                        self._last_score = loss
                        it += 1
                if hasattr(data, "reset"):
                    data.reset()
        self._pretrain_done = True

    def _step_extra_args(self) -> tuple:
        extra = ()
        if self._loss_scale_active:
            extra += (core.ensure_loss_scale_state(self),)
        if core.stat_guard_active(self):
            extra += (core.ensure_stat_guard_state(self),)
        return extra

    def fit_minibatch(self, ds) -> float:
        from deeplearning4j_tpu.datasets.api import ChunkedDataSet

        if isinstance(ds, ChunkedDataSet):
            # non-scan fallback: unstack and train per batch
            score = None
            for b in ds.to_datasets():
                score = self.fit_minibatch(b)
            return score
        if self.params is None:
            self.init()
        if self.conf.optimization_algo != "STOCHASTIC_GRADIENT_DESCENT":
            from deeplearning4j_tpu.optimize.solvers import (
                Solver,
                is_solver_algo,
            )

            if is_solver_algo(self.conf.optimization_algo):
                if self._solver is None:
                    self._solver = Solver(self)
                f, l, fm, lm = self._ds_arrays(ds)
                return self._solver.optimize(f, l, mask=lm, fmask=fm)
            raise ValueError(
                "Unknown optimization_algo "
                f"'{self.conf.optimization_algo}'"
            )
        if self._jit_step is None:
            self._jit_step = self._build_step()
        dtype = self._dtype()
        features = _as_list(getattr(ds, "features"))
        labels = _as_list(getattr(ds, "labels"))
        fmasks = _as_list(getattr(ds, "features_masks", None)
                          or getattr(ds, "features_mask", None))
        lmasks = _as_list(getattr(ds, "labels_masks", None)
                          or getattr(ds, "labels_mask", None))
        inputs = [jnp.asarray(f, dtype) for f in features]
        labels = [jnp.asarray(l, dtype) for l in labels]
        fmasks = [
            jnp.asarray(m, dtype) if m is not None else None for m in fmasks
        ] or None
        lmasks = [
            jnp.asarray(m, dtype) if m is not None else None for m in lmasks
        ] or None
        fwd = self.conf.tbptt_fwd_length
        if self.conf.backprop_type == "TruncatedBPTT" and any(
            x.ndim == 3 and x.shape[2] > fwd for x in inputs
        ):
            return self._fit_tbptt(inputs, labels, lmasks, fmasks)
        self._last_batch_rows = int(inputs[0].shape[0])
        core.check_grad_accum_batch(
            self.grad_accum, int(inputs[0].shape[0])
        )
        prof = profiler.get_active_profiler()
        if prof is not None:
            prof.begin_step(self.iteration_count + 1)
        score = None
        for _ in range(self.conf.iterations):
            if self._jit_step is None:
                # a listener may flip telemetry/guard mid-fit
                self._jit_step = self._build_step()
            lrs = self.updater_def.scheduled_lrs(self.iteration_count)
            t = jnp.asarray(self.iteration_count + 1, jnp.float32)
            rng = jax.random.fold_in(self._base_key, self.iteration_count)
            out = self._jit_step(
                self.params, self.updater_state, self.state,
                inputs, labels, lmasks, fmasks,
                {k: jnp.asarray(v, jnp.float32) for k, v in lrs.items()},
                t, rng, *self._step_extra_args(),
            )
            guard = self.divergence_guard
            score, ok = core.apply_step_out(self, out)
            self.iteration_count += 1
            self._last_score = score  # device array; sync deferred
            window = self._dispatch_window
            if window is not None:
                window.push(score, ok)
            elif guard is not None:
                if bool(ok):  # device sync — the cost of supervision
                    guard.good_step()
                else:
                    guard.bad_step(self)
            if self.listeners:
                lt0 = time.perf_counter()
                for listener in self.listeners:
                    listener.iteration_done(self, self.iteration_count)
                if prof is not None:
                    prof.note_listener_ms(
                        (time.perf_counter() - lt0) * 1e3)
            self._reset_recurrent_state()
        if prof is not None:
            prof.end_step(model=self, ds=ds, score=self._last_score,
                          grad_norm=getattr(self, "_last_grad_norm",
                                            None),
                          rows=self._last_batch_rows)
        return score  # 0-d device array; float() to sync

    def _fit_tbptt(self, inputs, labels, lmasks, fmasks) -> float:
        """Truncated BPTT for the DAG engine: slice every time-bearing
        array into ``tbptt_fwd_length`` chunks and carry recurrent
        state between chunks via the layer-state pytree (reference
        ``ComputationGraph.doTruncatedBPTT``). Non-time inputs ride
        along unchanged each chunk."""
        fwd = self.conf.tbptt_fwd_length
        t_lens = {x.shape[2] for x in inputs if x.ndim == 3}
        for group in (labels, lmasks, fmasks):
            for v in group or []:
                if v is not None and v.ndim == 3:
                    t_lens.add(v.shape[2])
        if len(t_lens) > 1:
            raise ValueError(
                "TruncatedBPTT requires every time-series input/label "
                f"to share one sequence length; got {sorted(t_lens)} "
                "(chunking mixed lengths would re-feed the shorter "
                "series each chunk with stale recurrent carry)"
            )
        t_total = t_lens.pop()

        def cut3(vs, s, e):
            if vs is None:
                return None
            return [
                v[:, :, s:e]
                if v is not None and v.ndim == 3 and v.shape[2] == t_total
                else v
                for v in vs
            ]

        def cut_mask(vs, s, e):
            if vs is None:
                return None
            return [
                m[:, s:e]
                if m is not None and m.ndim == 2 and m.shape[1] == t_total
                else m
                for m in vs
            ]

        if self._jit_step is None:
            self._jit_step = self._build_step()
        self._reset_recurrent_state()
        score = None
        for start in range(0, t_total, fwd):
            end = min(start + fwd, t_total)
            lrs = self.updater_def.scheduled_lrs(self.iteration_count)
            t = jnp.asarray(self.iteration_count + 1, jnp.float32)
            rng = jax.random.fold_in(
                self._base_key, self.iteration_count
            )
            out = self._jit_step(
                self.params, self.updater_state, self.state,
                cut3(inputs, start, end), cut3(labels, start, end),
                cut_mask(lmasks, start, end),
                cut_mask(fmasks, start, end),
                {k: jnp.asarray(v, jnp.float32) for k, v in lrs.items()},
                t, rng, *self._step_extra_args(),
            )
            guard = self.divergence_guard
            score, ok = core.apply_step_out(self, out)
            self.iteration_count += 1
            self._last_score = score
            if guard is not None:
                if bool(ok):
                    guard.good_step()
                else:
                    guard.bad_step(self)
            for listener in self.listeners:
                listener.iteration_done(self, self.iteration_count)
        self._reset_recurrent_state()
        return score

    def _reset_recurrent_state(self) -> None:
        for n in self.layer_vertex_names:
            layer = self.conf.vertices[n].layer_conf
            if layer.is_recurrent():
                self.state[n] = {}

    # ------------------------------------------------------------------

    def _output_fn(self):
        """Pure inference forward closure shared by the jitted
        ``output`` path and the AOT export (identical trace ->
        bitwise identical results)."""
        def out_fn(params, state, inputs, fmasks):
            values, _, _ = self._forward_values(
                params, state, inputs, train=False, rng=None,
                fmasks=fmasks, use_scan=True,
            )
            return [values[n] for n in self.conf.outputs]
        return out_fn

    def output(self, *inputs, features_masks=None) -> List[jax.Array]:
        """Activated values of the output vertices (reference
        ``ComputationGraph.output``). ``features_masks``: per-graph-
        input [b, t] masks threaded to recurrent branches (reference
        ``output(..., featureMaskArrays)``)."""
        if self.params is None:
            self.init()
        dtype = self._dtype()
        if self._aot_outputs and features_masks is None:
            fn = self._aot_outputs.get(tuple(
                tuple(int(d) for d in np.shape(x)) for x in inputs
            ))
            if fn is not None:
                return fn(self.params, self.state,
                          [jnp.asarray(x, dtype) for x in inputs])
        if self._jit_output is None:
            self._jit_output = jax.jit(self._output_fn())
        arr = [jnp.asarray(x, dtype) for x in inputs]
        fm = None
        if features_masks is not None:
            fm = [
                None if m is None else jnp.asarray(m, dtype)
                for m in _as_list(features_masks)
            ]
        return self._jit_output(self.params, self.state, arr, fm)

    # -- AOT export/install (compile/aot.py) ---------------------------

    def _aot_shape_key(self, shapes) -> tuple:
        """Normalize to the nested key form: one shape -> a 1-tuple
        of shape tuples (the DAG engine is list-of-inputs shaped)."""
        shapes = tuple(shapes)
        if shapes and not isinstance(shapes[0], (tuple, list)):
            shapes = (shapes,)
        return tuple(tuple(int(d) for d in s) for s in shapes)

    def _output_kind(self) -> str:
        # scan AND kernel dispatch both change the compiled inference
        # program (conv/dense kernels + the eval conv->BN peephole)
        return ("output" + ("+scan" if self.scan_layers else "")
                + core.kernel_kind_suffix(self))

    def aot_fingerprint(self, shapes, kind: Optional[str] = None) -> str:
        from deeplearning4j_tpu.compile.aot import artifact_fingerprint

        return artifact_fingerprint(
            self.conf.to_dict(), self._aot_shape_key(shapes),
            str(self._dtype()),
            kind if kind is not None else self._output_kind(),
        )

    def aot_export_output(self, shapes, registry=None) -> bytes:
        """Serialize the compiled inference forward for inputs of
        exactly ``shapes`` (one shape tuple, or a tuple of them for
        multi-input graphs; inference mode, no masks)."""
        if self.params is None:
            self.init()
        from deeplearning4j_tpu.compile.aot import export_artifact

        key = self._aot_shape_key(shapes)
        dtype = self._dtype()
        base = self._output_fn()
        fn = jax.jit(lambda p, s, arr: base(p, s, arr, None))
        specs = [jax.ShapeDtypeStruct(s, dtype) for s in key]
        return export_artifact(
            fn, (self.params, self.state, specs),
            fingerprint=self.aot_fingerprint(key),
            shape=key, kind=self._output_kind(),
            name="output-" + "+".join(
                "x".join(str(d) for d in s) for s in key
            ),
            registry=registry,
        )

    def aot_install_output(self, shapes, artifact,
                           registry=None) -> bool:
        """Install an inference executable for exactly ``shapes``
        from artifact bytes (fingerprint-checked; stale/corrupt
        artifacts are refused silently) or a callable."""
        key = self._aot_shape_key(shapes)
        if callable(artifact):
            self._aot_outputs[key] = artifact
            return True
        from deeplearning4j_tpu.compile.aot import load_artifact

        fn = load_artifact(
            artifact,
            expected_fingerprint=self.aot_fingerprint(key),
            registry=registry,
        )
        if fn is None:
            return False
        self._aot_outputs[key] = fn
        return True

    def aot_output_shapes(self) -> List[tuple]:
        return list(self._aot_outputs)

    def _step_kind(self) -> str:
        """AOT kind string for the train step: guard/telemetry flags
        and whole-net transforms are part of the artifact identity
        (same scheme as MultiLayerNetwork)."""
        return (
            "step"
            + ("+guard" if self.divergence_guard is not None else "")
            + ("+telemetry" if self._telemetry_grad_norm else "")
            + core.transform_kind_suffix(self)
        )

    def aot_export_step(self, ds, registry=None) -> bytes:
        """Serialize the compiled train step specialized to ``ds``'s
        input/label shapes (no masks)."""
        if self.params is None:
            self.init()
        from deeplearning4j_tpu.compile.aot import export_artifact

        dtype = self._dtype()
        inputs = [jnp.asarray(f, dtype)
                  for f in _as_list(ds.features)]
        labels = [jnp.asarray(l, dtype) for l in _as_list(ds.labels)]
        lrs = {
            k: jnp.asarray(v, jnp.float32) for k, v in
            self.updater_def.scheduled_lrs(self.iteration_count).items()
        }
        t = jnp.asarray(1, jnp.float32)
        rng = jax.random.fold_in(self._base_key, 0)
        x_key = tuple(tuple(int(d) for d in a.shape) for a in inputs)
        y_key = tuple(tuple(int(d) for d in a.shape) for a in labels)
        return export_artifact(
            self._build_step(),
            (self.params, self.updater_state, self.state, inputs,
             labels, None, None, lrs, t, rng)
            + self._step_extra_args(),
            fingerprint=self.aot_fingerprint(
                x_key, kind=self._step_kind()
            ),
            shape=x_key, kind=self._step_kind(),
            name="step-" + "+".join(
                "x".join(str(d) for d in s) for s in x_key
            ),
            meta_extra={"label_shape": [list(s) for s in y_key]},
            registry=registry,
        )

    def aot_install_step(self, artifact, registry=None) -> bool:
        """Install an AOT train-step executable as ``_jit_step``
        (matching shapes run the restored executable; anything else
        lazily JITs — ``compile.aot.AotStepFunction``)."""
        from deeplearning4j_tpu.compile.aot import (
            AotStepFunction,
            load_artifact,
            peek_meta,
        )

        try:
            meta = peek_meta(artifact)
            x_key = self._aot_shape_key(meta["shape"])
            y_key = self._aot_shape_key(meta["label_shape"])
        except Exception:
            return False
        fn = load_artifact(
            artifact,
            expected_fingerprint=self.aot_fingerprint(
                x_key, kind=self._step_kind()
            ),
            registry=registry,
        )
        if fn is None:
            return False
        self._jit_step = AotStepFunction(
            fn, x_key, y_key, self._build_step
        )
        return True

    def output_padded(self, *inputs, n_valid, features_masks=None):
        """Inference on row-padded batches: every graph input is
        padded to the same bucketed row count; returns each output
        vertex's activations sliced back to the first ``n_valid``
        rows. Same contract as ``MultiLayerNetwork.output_padded`` —
        shares ``output``'s jitted program (one executable per bucket
        shape), relies on row-independence of inference-mode vertices
        (enforced bitwise by ``tests/test_batching.py``), and
        composes ``features_masks`` that cover only the valid rows
        with all-ones padding rows."""
        n = int(n_valid)
        if not inputs:
            raise ValueError("output_padded needs at least one input")
        b = int(np.shape(inputs[0])[0])
        if not 0 < n <= b:
            raise ValueError(
                f"n_valid must be in [1, {b}] for a {b}-row batch; "
                f"got {n}"
            )
        fms = features_masks
        if fms is not None:
            padded_fms = []
            for m in _as_list(fms):
                if m is not None:
                    m = np.asarray(m)
                    if m.shape[0] == n and n < b:
                        m = np.concatenate(
                            [m, np.ones((b - n,) + m.shape[1:],
                                        m.dtype)],
                            axis=0,
                        )
                    elif m.shape[0] != b:
                        raise ValueError(
                            f"features_mask covers {m.shape[0]} rows;"
                            f" expected {n} (valid) or {b} (padded)"
                        )
                padded_fms.append(m)
            fms = padded_fms
        outs = self.output(*inputs, features_masks=fms)
        return [o[:n] for o in outs]

    def feed_forward(self, *inputs, train: bool = False) -> Dict[str, Any]:
        """Activations of EVERY vertex by name (reference
        ``ComputationGraph.feedForward`` returns the activation map) —
        scan-over-layers stays off here so inner chain members'
        values are materialized."""
        if self.params is None:
            self.init()
        dtype = self._dtype()
        arr = [jnp.asarray(x, dtype) for x in inputs]
        # train=True must apply dropout like the fit path does
        rng = (
            jax.random.fold_in(self._base_key, self.iteration_count)
            if train else None
        )
        values, _, _ = self._forward_values(
            self.params, self.state, arr, train=train, rng=rng
        )
        return values

    def rnn_time_step(self, *inputs) -> List[jax.Array]:
        """Feed one (or a few) timesteps per input, carrying recurrent
        state across calls (reference ``ComputationGraph.rnnTimeStep``,
        ``ComputationGraph.java:1748``). Inputs [b, size] or
        [b, size, t]; returns the output vertices' activations with the
        same time-axis convention as the inputs."""
        if self.params is None:
            self.init()
        for n in self.layer_vertex_names:
            lc = self.conf.vertices[n].layer_conf
            if not lc.can_stream():
                raise ValueError(
                    f"Vertex '{n}' ({type(lc).__name__}) cannot be used "
                    "with rnn_time_step — it needs the full sequence "
                    "(reference throws UnsupportedOperationException)"
                )
        dtype = self._dtype()
        arr = [jnp.asarray(x, dtype) for x in inputs]
        # each [b, size] input gets a singleton time axis independently;
        # outputs come back 2-d only when EVERY input arrived 2-d
        was_2d = [x.ndim == 2 for x in arr]
        squeeze = bool(arr) and all(was_2d)
        arr = [x[:, :, None] if w else x for x, w in zip(arr, was_2d)]
        t_new = max(
            (int(x.shape[2]) for x in arr if x.ndim == 3), default=1
        )
        named = [
            (n, self.conf.vertices[n].layer_conf)
            for n in self.layer_vertex_names
        ]
        core.stream_guard_and_prime(
            named, self._rnn_state, self._stream_steps, t_new,
            int(arr[0].shape[0]) if arr else 1, dtype,
        )
        merged = dict(self.state)
        for name, carry in self._rnn_state.items():
            merged[name] = {**merged.get(name, {}), **carry}
        if self._jit_rnn_step is None:
            def rnn_step(params, state, inputs):
                values, _, new_state = self._forward_values(
                    params, state, inputs, train=False, rng=None
                )
                return [values[n] for n in self.conf.outputs], new_state
            self._jit_rnn_step = jax.jit(rnn_step)
        outs, new_state = self._jit_rnn_step(self.params, merged, arr)
        core.extract_stream_state(named, new_state, self._rnn_state)
        self._stream_steps += t_new
        return [o[:, :, 0] if squeeze and o.ndim == 3 else o
                for o in outs]

    def rnn_clear_previous_state(self) -> None:
        """Reference ``rnnClearPreviousState``."""
        self._rnn_state = {}
        self._stream_steps = 0

    def score(self, ds) -> float:
        dtype = self._dtype()
        features = [jnp.asarray(f, dtype) for f in _as_list(ds.features)]
        labels = [jnp.asarray(l, dtype) for l in _as_list(ds.labels)]
        lmasks = _as_list(getattr(ds, "labels_masks", None)
                          or getattr(ds, "labels_mask", None)) or None
        fmasks = _as_list(getattr(ds, "features_masks", None)
                          or getattr(ds, "features_mask", None)) or None
        if lmasks:
            lmasks = [
                jnp.asarray(m, dtype) if m is not None else None
                for m in lmasks
            ]
        if fmasks:
            fmasks = [
                jnp.asarray(m, dtype) if m is not None else None
                for m in fmasks
            ]
        s, _ = self._score_pure(
            self.params, self.state, features, labels, lmasks, None,
            train=False, fmasks=fmasks,
        )
        return float(s)

    def evaluate(self, iterator):
        from deeplearning4j_tpu.datasets.api import ChunkedDataSet
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        e = Evaluation()
        for item in iterator:
            batches = (
                item.to_datasets() if isinstance(item, ChunkedDataSet)
                else [item]
            )
            for ds in batches:
                self._evaluate_one(e, ds)
        if hasattr(iterator, "reset"):
            iterator.reset()
        return e

    def _evaluate_one(self, e, ds) -> None:
        fm = (getattr(ds, "features_masks", None)
              or getattr(ds, "features_mask", None))
        out = self.output(
            *_as_list(ds.features), features_masks=fm
        )[0]
        labels = np.asarray(_as_list(ds.labels)[0])
        m = _as_list(getattr(ds, "labels_masks", None)
                     or getattr(ds, "labels_mask", None))
        mask = m[0] if m else None
        if mask is None and labels.ndim == 3:
            # per-timestep labels without a labels mask: fall back
            # to the features mask (same rule as MLN.evaluate);
            # 2-d per-sequence labels must not take a [b, t] mask
            fml = _as_list(fm)
            mask = fml[0] if fml else None
        e.eval(labels, np.asarray(out),
               mask=np.asarray(mask) if mask is not None else None)

    # ------------------------------------------------------------------

    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def copy(self) -> "ComputationGraph":
        # Deep-copy device buffers (the jitted step donates them).
        clone = lambda a: jnp.array(a, copy=True)
        g = ComputationGraph(self.conf)
        g.init(params=jax.tree_util.tree_map(clone, self.params))
        g.updater_state = jax.tree_util.tree_map(clone, self.updater_state)
        g.state = jax.tree_util.tree_map(clone, self.state)
        return g

    def num_params(self) -> int:
        return sum(
            int(np.prod(p.shape))
            for lp in self.params.values()
            for p in lp.values()
        )

    def _flat_order(self) -> List[Tuple[str, str]]:
        order = []
        for name in self.layer_vertex_names:
            pnames = list(self.params[name].keys())
            preferred = [p for p in ("W", "b") if p in pnames]
            rest = [p for p in pnames if p not in ("W", "b")]
            for pn in preferred + sorted(rest):
                order.append((name, pn))
        return order

    def params_flat(self) -> np.ndarray:
        return np.concatenate([
            np.asarray(self.params[ln][pn]).ravel()
            for ln, pn in self._flat_order()
        ]) if self.params else np.zeros((0,))

    def set_params_flat(self, vec) -> None:
        vec = np.asarray(vec)
        off = 0
        for ln, pn in self._flat_order():
            p = self.params[ln][pn]
            n = int(np.prod(p.shape))
            self.params[ln][pn] = jnp.asarray(
                vec[off:off + n].reshape(p.shape), p.dtype
            )
            off += n

    def summary(self) -> str:
        lines = ["=" * 72]
        lines.append(f"{'vertex':<20}{'type':<30}{'params':>10}")
        lines.append("-" * 72)
        total = 0
        for name in self.topo:
            v = self.conf.vertices[name]
            n = 0
            if self.params and name in self.params:
                n = sum(
                    int(np.prod(p.shape))
                    for p in self.params[name].values()
                )
            total += n
            tname = (
                type(v.layer_conf).__name__ if isinstance(v, LayerVertex)
                else type(v).__name__
            )
            lines.append(f"{name:<20}{tname:<30}{n:>10}")
        lines.append("-" * 72)
        lines.append(f"Total params: {total}")
        lines.append("=" * 72)
        return "\n".join(lines)
