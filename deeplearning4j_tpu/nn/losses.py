"""Loss functions (reference: nd4j ``ILossFunction`` impls used through
``LossFunctions.LossFunction`` enum names on output-layer configs).

Semantics mirror the reference: a loss consumes the output layer's
*pre-activation* plus the layer's activation name, so numerically fused
stable paths are used for softmax+MCXENT and sigmoid+XENT (the reference
gets stability from dedicated native ops; we get it from log-space
formulations that XLA fuses).

Shape convention:
- 2-d labels/preout: ``[batch, nOut]`` — one score row per example.
- 3-d (RNN): ``[batch, nOut, time]`` — one score row per (example,
  timestep), with an optional ``[batch, time]`` mask; masked timesteps
  contribute zero score and zero gradient (reference: mask-aware losses
  exercised by ``GradientCheckTestsMasking``).

Gradients are obtained by ``jax.grad`` through these scores — there is
no hand-written ``computeGradient`` twin to keep in sync (the reference
maintains both and gradient-checks them against each other; here they
are one function by construction).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations

_EPS = 1e-8

# Each row fn: (labels2d, preout2d, activation_name) -> per-row score [rows]


def _activate(preout: jax.Array, activation: str) -> jax.Array:
    if activation == "softmax":
        return jax.nn.softmax(preout, axis=-1)
    return activations.get(activation)(preout)


def _mse(labels, preout, act):
    d = _activate(preout, act) - labels
    return jnp.sum(d * d, axis=-1) / labels.shape[-1]


def _l2(labels, preout, act):
    d = _activate(preout, act) - labels
    return jnp.sum(d * d, axis=-1)


def _l1(labels, preout, act):
    return jnp.sum(jnp.abs(_activate(preout, act) - labels), axis=-1)


def _mae(labels, preout, act):
    return _l1(labels, preout, act) / labels.shape[-1]


def _mape(labels, preout, act):
    out = _activate(preout, act)
    return 100.0 * jnp.sum(
        jnp.abs((labels - out) / (jnp.abs(labels) + _EPS)), axis=-1
    ) / labels.shape[-1]


def _msle(labels, preout, act):
    out = _activate(preout, act)
    d = jnp.log1p(jnp.maximum(out, -1 + _EPS)) - jnp.log1p(
        jnp.maximum(labels, -1 + _EPS)
    )
    return jnp.sum(d * d, axis=-1) / labels.shape[-1]


def _xent(labels, preout, act):
    """Binary cross-entropy; stable-from-logits when act == sigmoid."""
    if act == "sigmoid":
        # log(sigmoid(x)) = -softplus(-x); log(1-sigmoid(x)) = -softplus(x)
        return jnp.sum(
            labels * jax.nn.softplus(-preout)
            + (1.0 - labels) * jax.nn.softplus(preout),
            axis=-1,
        )
    out = jnp.clip(_activate(preout, act), _EPS, 1.0 - _EPS)
    return -jnp.sum(
        labels * jnp.log(out) + (1.0 - labels) * jnp.log(1.0 - out), axis=-1
    )


def _mcxent(labels, preout, act):
    """Multi-class cross-entropy; stable-from-logits when act == softmax."""
    if act == "softmax":
        return -jnp.sum(labels * jax.nn.log_softmax(preout, axis=-1), axis=-1)
    out = jnp.clip(_activate(preout, act), _EPS, 1.0)
    return -jnp.sum(labels * jnp.log(out), axis=-1)


def _kl(labels, preout, act):
    out = jnp.clip(_activate(preout, act), _EPS, 1.0)
    lab = jnp.clip(labels, _EPS, 1.0)
    return jnp.sum(labels * (jnp.log(lab) - jnp.log(out)), axis=-1)


def _cosine(labels, preout, act):
    out = _activate(preout, act)
    num = jnp.sum(labels * out, axis=-1)
    den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1)
    return -num / (den + _EPS)


def _hinge(labels, preout, act):
    # labels in {-1, +1}
    return jnp.sum(jnp.maximum(0.0, 1.0 - labels * _activate(preout, act)), axis=-1)


def _squared_hinge(labels, preout, act):
    h = jnp.maximum(0.0, 1.0 - labels * _activate(preout, act))
    return jnp.sum(h * h, axis=-1)


def _poisson(labels, preout, act):
    out = jnp.maximum(_activate(preout, act), _EPS)
    return jnp.sum(out - labels * jnp.log(out), axis=-1)


def _nll(labels, preout, act):
    return _mcxent(labels, preout, act)


_REGISTRY: dict[str, Callable] = {
    "MSE": _mse,
    "SQUARED_LOSS": _l2,
    "L2": _l2,
    "L1": _l1,
    "MEAN_ABSOLUTE_ERROR": _mae,
    "MEAN_ABSOLUTE_PERCENTAGE_ERROR": _mape,
    "MEAN_SQUARED_LOGARITHMIC_ERROR": _msle,
    "XENT": _xent,
    "MCXENT": _mcxent,
    "NEGATIVELOGLIKELIHOOD": _nll,
    "RECONSTRUCTION_CROSSENTROPY": _xent,
    "KL_DIVERGENCE": _kl,
    "COSINE_PROXIMITY": _cosine,
    "HINGE": _hinge,
    "SQUARED_HINGE": _squared_hinge,
    "POISSON": _poisson,
}


def names() -> list[str]:
    return sorted(_REGISTRY)


def register(name: str, row_fn: Callable) -> None:
    """Register a custom loss (reference analog: custom ILossFunction
    with JSON subtype registration)."""
    _REGISTRY[name.upper()] = row_fn


def _to_rows(a: jax.Array) -> jax.Array:
    """[b, n] -> [b, n]; [b, n, t] -> [b*t, n] (reference reshapes RNN
    output to 2-d before loss, ``RnnOutputLayer``)."""
    if a.ndim == 2:
        return a
    if a.ndim == 3:
        return jnp.transpose(a, (0, 2, 1)).reshape(-1, a.shape[1])
    raise ValueError(f"Loss expects 2-d or 3-d arrays, got shape {a.shape}")


def score(
    loss: str,
    labels: jax.Array,
    preout: jax.Array,
    activation: str,
    mask: jax.Array | None = None,
    average: bool = True,
) -> jax.Array:
    """Scalar loss score (reference ``ILossFunction.computeScore``).

    ``average=True`` divides by the number of unmasked rows (examples,
    or example-timesteps for RNN), matching the reference's
    minibatch-averaged score.
    """
    rows = per_row_scores(loss, labels, preout, activation, mask)
    total = jnp.sum(rows)
    if not average:
        return total
    if mask is not None:
        count = jnp.maximum(jnp.sum(mask), 1.0)
    else:
        count = rows.shape[0]
    return total / count


def per_row_scores(
    loss: str,
    labels: jax.Array,
    preout: jax.Array,
    activation: str,
    mask: jax.Array | None = None,
) -> jax.Array:
    """Per-row (example / example-timestep) scores, mask applied."""
    try:
        fn = _REGISTRY[loss.upper()]
    except KeyError:
        raise ValueError(f"Unknown loss '{loss}'. Known: {names()}") from None
    rows = fn(_to_rows(labels), _to_rows(preout), activation)
    if mask is not None:
        rows = rows * _to_row_mask(mask, labels)
    return rows


def _to_row_mask(mask: jax.Array, labels: jax.Array) -> jax.Array:
    """[b] (2-d case) or [b, t] -> flat row mask aligned with _to_rows."""
    if labels.ndim == 2:
        return mask.reshape(-1)
    return mask.reshape(-1)  # [b, t] row-major matches transpose(0,2,1) flatten
