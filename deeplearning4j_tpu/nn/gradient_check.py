"""Numeric gradient checking (reference:
``gradientcheck/GradientCheckUtil.java:62`` — the backbone of the
reference's correctness suite).

Central differences on the flat parameter vector vs the analytic
gradient. In the reference this validates hand-written
``backpropGradient`` implementations; here the analytic side is
``jax.grad`` through the same forward, so the check validates the whole
composition (layer math, preprocessors, losses, masking) in float64.

Default tolerances match the reference (``GradientCheckTests.java:
40-42``): eps=1e-6, maxRelError=1e-3, minAbsError=1e-8, run in double
precision (the helper enables x64 only for its own scope via the
``jax.enable_x64`` context manager, leaving global state untouched).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@contextmanager
def f64_mode():
    """x64 enabled AND pinned to the CPU backend when the default
    backend is a TPU: TPUs have no native float64, so f64 central
    differences run on host — the same discipline as the reference,
    whose double-precision gradient checks run on the native CPU
    backend. GPUs keep their native f64."""
    from deeplearning4j_tpu.ops.dispatch import cpu_device

    ctx_dev = (
        cpu_device() if jax.default_backend() == "tpu" else None
    )
    with jax.enable_x64(True):
        if ctx_dev is not None:
            with jax.default_device(ctx_dev):
                yield
        else:
            yield


def check_gradients(
    model,
    x,
    labels,
    mask: Optional[np.ndarray] = None,
    *,
    eps: float = 1e-6,
    max_rel_error: float = 1e-3,
    min_abs_error: float = 1e-8,
    max_per_param: Optional[int] = None,
    print_results: bool = False,
    seed: int = 0,
    train: bool = False,
    features_mask: Optional[np.ndarray] = None,
    rng_key=None,
) -> bool:
    """Returns True if all checked parameters pass.

    ``max_per_param`` subsamples elements per parameter array (the
    reference checks every element; for large nets subsampling keeps
    the O(2·P) forward passes tractable — pass None for full parity).
    """
    with f64_mode():
        return _check_gradients_x64(
            model, x, labels, mask,
            eps=eps, max_rel_error=max_rel_error,
            min_abs_error=min_abs_error, max_per_param=max_per_param,
            print_results=print_results, seed=seed, train=train,
            features_mask=features_mask, rng_key=rng_key,
        )


def _check_gradients_x64(
    model, x, labels, mask=None, *, eps, max_rel_error, min_abs_error,
    max_per_param, print_results, seed, train, features_mask,
    rng_key=None,
) -> bool:
    if model.params is None:
        model.init()

    f64 = lambda t: jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.float64), t
    )
    params = f64(model.params)
    state = f64(model.state)
    x64 = jnp.asarray(np.asarray(x), jnp.float64)
    y64 = jnp.asarray(np.asarray(labels), jnp.float64)
    m64 = jnp.asarray(np.asarray(mask), jnp.float64) if mask is not None else None
    fm64 = (
        jnp.asarray(np.asarray(features_mask), jnp.float64)
        if features_mask is not None else None
    )

    def score_fn(p):
        # rng_key (when given) is FIXED across every central-difference
        # evaluation, so stochastic regularizers (dropout/DropConnect)
        # present one frozen mask to both sides of the check
        s, _ = model._score_pure(
            p, state, x64, y64, m64, rng_key, train=train, fmask=fm64
        )
        return s

    score_jit = jax.jit(score_fn)
    analytic = jax.grad(score_fn)(params)

    rng = np.random.RandomState(seed)
    all_pass = True
    total_checked = 0
    total_failed = 0
    for ln, pn in model._flat_order():
        a_grad = np.asarray(analytic[ln][pn]).ravel()
        base = np.asarray(params[ln][pn], dtype=np.float64)
        flat = base.ravel().copy()
        n = flat.size
        idxs = np.arange(n)
        if max_per_param is not None and n > max_per_param:
            idxs = rng.choice(n, size=max_per_param, replace=False)
        for i in idxs:
            orig = flat[i]
            flat[i] = orig + eps
            p_plus = dict(params)
            lp = dict(p_plus[ln])
            lp[pn] = jnp.asarray(flat.reshape(base.shape))
            p_plus[ln] = lp
            s_plus = float(score_jit(p_plus))
            flat[i] = orig - eps
            lp2 = dict(params[ln])
            lp2[pn] = jnp.asarray(flat.reshape(base.shape))
            p_minus = dict(params)
            p_minus[ln] = lp2
            s_minus = float(score_jit(p_minus))
            flat[i] = orig
            numeric = (s_plus - s_minus) / (2.0 * eps)
            analytic_i = float(a_grad[i])
            abs_err = abs(numeric - analytic_i)
            denom = max(abs(numeric), abs(analytic_i))
            rel_err = abs_err / denom if denom > 0 else 0.0
            total_checked += 1
            if rel_err > max_rel_error and abs_err > min_abs_error:
                total_failed += 1
                all_pass = False
                if print_results:
                    print(
                        f"FAIL {ln}.{pn}[{i}]: analytic={analytic_i:.8g} "
                        f"numeric={numeric:.8g} relErr={rel_err:.4g}"
                    )
    if print_results:
        print(
            f"Gradient check: {total_checked - total_failed}/{total_checked} "
            f"passed"
        )
    return all_pass
