"""MultiLayerNetwork — the sequential-stack model (reference:
``nn/multilayer/MultiLayerNetwork.java``, 2,534 LoC).

TPU-first redesign of the reference's imperative engine:

- The reference's ``fit`` path crosses JVM->JNI->libnd4j per op
  (SURVEY.md §3.1); here the ENTIRE minibatch step — forward, loss,
  backward (``jax.grad``), gradient normalization, updater, parameter
  step — is one jitted XLA program per input shape, compiled once and
  cached. Parameters/updater-state buffers are donated so the step
  updates in place in HBM.
- The reference flattens params into one 1-D view array
  (``init():367``); the idiomatic equivalent is a pytree
  ``{layer: {name: array}}`` (shards naturally under pjit). A flat view
  is still offered for serializer/tooling parity
  (``params_flat``/``set_params_flat``).
- Backprop (``calcBackpropGradients:1134``) does not exist as code:
  ``jax.grad`` differentiates the same forward used for inference.

This class is a thin wrapper around the unified functional core
(``nn/core.py``): the pure forward/score, the jitted step builders,
the scan-fused multi-step, the fit drivers, and the whole-net
transforms (scan-over-layers, activation remat, dynamic loss scaling)
are all implemented there ONCE and shared with ``ComputationGraph``
(enforced by ``scripts/lint_parity.py``).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import core
from deeplearning4j_tpu.observability import profiler
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.preprocessors import ShapeContext
from deeplearning4j_tpu.nn.updaters import MultiLayerUpdaterDef

# Compatibility aliases: these helpers grew up in this module and are
# imported from here by older call sites; the canonical definitions
# live in the functional core now.
_dtype_of = core.dtype_of
_compute_dtype_of = core.compute_dtype_of
_cast_floats = core.cast_floats
_to_device = core.to_device
_cast_stacked = core.cast_stacked
_stack_on_device = core.stack_on_device
_nbytes = core.nbytes
_iter_unchunked = core.iter_unchunked
_reg_penalty = core.reg_penalty
_scan_consts = core.scan_consts
_note_it0 = core.note_it0
_cached_epoch_plan = core.cached_epoch_plan
_build_scan_plan = core.build_scan_plan
_stream_guard_and_prime = core.stream_guard_and_prime
_extract_stream_state = core.extract_stream_state


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layer_names: List[str] = [
            conf.layer_name(i) for i in range(len(conf.layers))
        ]
        if len(set(self.layer_names)) != len(self.layer_names):
            from deeplearning4j_tpu.exceptions import (
                DL4JInvalidConfigException,
            )

            raise DL4JInvalidConfigException(
                "Duplicate layer names in configuration"
            )
        self.params: Optional[Dict[str, Dict[str, jax.Array]]] = None
        self.state: Dict[str, dict] = {}
        self.updater_def = MultiLayerUpdaterDef({
            name: layer.updater_settings()
            for name, layer in zip(self.layer_names, conf.layers)
        })
        self.updater_state = None
        self.iteration_count = 0
        self.epoch_count = 0
        self._last_score = float("nan")
        self.listeners: List[Any] = []
        self._rnn_state: Dict[str, Any] = {}   # streaming rnnTimeStep state
        self._stream_steps = 0  # timesteps consumed vs finite caches
        self._jit_step = None
        self._jit_multi_step = None
        self._jit_tbptt_multi_step = None
        self._solver = None  # lazily built for LBFGS/CG/line-search
        self.scan_chunk = 16  # minibatches fused per dispatch
        # multi-epoch fits keep the dataset HBM-resident up to this
        # size, derived from the device's reported memory limit
        # (4 GiB fallback when the runtime exposes no memory_stats())
        from deeplearning4j_tpu.util.device import device_cache_budget_bytes

        self.device_cache_bytes = device_cache_budget_bytes()
        self._jit_output = None
        # AOT-restored inference executables by exact input shape
        # (compile/aot.py): consulted by output() before the jit
        # path, so a warm restart serves without ever building
        # _jit_output. Empty dict = one falsy check on the hot path.
        self._aot_outputs: Dict[Tuple[int, ...], Callable] = {}
        self._jit_rnn_step = None
        self._jit_pretrain_steps: Dict[int, Callable] = {}
        self._jit_pretrain_input = None
        self._pretrain_done = False
        # device-resident scan constants (see core.scan_consts)
        self._scan_const_cache: Dict[Any, Any] = {}
        self._it0_dev = None
        self._it0_shadow = -1
        self._base_key = jax.random.PRNGKey(conf.seed)
        # resilience.DivergenceGuard (set_divergence_guard): when set,
        # the jitted step suppresses non-finite updates in-jit and the
        # host applies skip/rollback policy; forces the per-step path
        # (the fused scan cannot consult the guard mid-dispatch)
        self.divergence_guard = None
        # async dispatch knobs (the fit loop runs through an
        # AsyncDispatchWindow): at most max_in_flight steps
        # dispatched-but-incomplete; the guard's ok-flag is collected
        # guard_lag steps late (None -> max_in_flight; rollback policy
        # forces 0 — see parallel/dispatch.py)
        self.max_in_flight = 2
        self.guard_lag = None
        self._dispatch_window = None
        # observability.TelemetryListener (enable_step_telemetry):
        # when set, the jitted step also returns the gradient global
        # L2 norm — one fused scalar, read lazily by the listener
        self._telemetry_grad_norm = False
        self._last_grad_norm = None  # 0-d device array; float() syncs
        self._last_batch_rows = None  # host int; examples/sec signal
        # whole-net transform knobs (scan_layers / remat / loss_scale)
        # — see core.set_transforms; seeded from config hints
        core.init_transforms(self, conf)

    @property
    def score_value(self) -> float:
        """Latest minibatch score. Reading this syncs with the device
        (the jitted step returns the score as a device scalar and does
        NOT block — throughput-critical loops should avoid reading it
        every step; PerformanceListener doesn't)."""
        return float(self._last_score)

    @score_value.setter
    def score_value(self, v) -> None:
        self._last_score = v

    # ------------------------------------------------------------------
    # init (reference MultiLayerNetwork.init():367)
    # ------------------------------------------------------------------

    def init(self, params: Optional[dict] = None) -> "MultiLayerNetwork":
        dtype = _dtype_of(self.conf)
        if params is not None:
            # checkpoint npz round-trips drop empty entries; param-less
            # layers (pooling, activation) get their {} slot back, but
            # a missing PARAMETERIZED layer is checkpoint corruption —
            # fail here, not at a KeyError deep inside the first trace
            restored = {}
            for name, layer in zip(self.layer_names, self.conf.layers):
                if name in params:
                    restored[name] = params[name]
                elif layer.init_params(self._base_key, dtype):
                    raise ValueError(
                        f"checkpoint has no params for layer '{name}' "
                        f"({type(layer).__name__})"
                    )
                else:
                    restored[name] = {}
            self.params = restored
        else:
            keys = jax.random.split(
                self._base_key, max(len(self.conf.layers), 1)
            )
            self.params = {
                name: layer.init_params(k, dtype)
                for name, layer, k in zip(
                    self.layer_names, self.conf.layers, keys
                )
            }
        self.state = {
            name: layer.init_state(dtype)
            for name, layer in zip(self.layer_names, self.conf.layers)
        }
        self.updater_state = self.updater_def.init(self.params)
        self._pretrain_done = False  # fresh params ⇒ pretrain again
        return self

    # ------------------------------------------------------------------
    # whole-net transforms (implemented once in nn/core.py)
    # ------------------------------------------------------------------

    def set_transforms(self, scan_layers=None, remat=None,
                       loss_scale=None,
                       megastep=None) -> "MultiLayerNetwork":
        """(Re)configure the whole-net transforms: ``scan_layers``
        (stack homogeneous layer runs under one ``lax.scan`` —
        O(depth) HLO becomes O(1), collapsing deep-stack compile
        time), ``remat`` (``none | dots_saveable | full`` activation
        rematerialization via ``jax.checkpoint`` — recompute FLOPs
        for activation HBM), ``loss_scale`` (dynamic loss scaling
        for ``compute_dtype="float16"``; True = default 2**15), and
        ``megastep`` (K>1 folds K optimizer steps + on-device metric
        accumulation into ONE XLA dispatch, read back once per
        chunk). Trajectories are bitwise identical with the
        transforms on or off; changed knobs invalidate the compiled
        programs."""
        core.set_transforms(self, scan_layers, remat, loss_scale,
                            megastep)
        return self

    @property
    def _loss_scale_active(self) -> bool:
        return core.loss_scale_active(self)

    def _active_layer_runs(self) -> tuple:
        if self._layer_runs_cache is None:
            self._layer_runs_cache = tuple(core.detect_layer_runs(
                self.conf.layers, self.conf.preprocessors
            ))
        return self._layer_runs_cache

    def scan_layer_run_count(self) -> int:
        """Active scanned layer runs (telemetry signal)."""
        return len(self._active_layer_runs()) if self.scan_layers else 0

    # ------------------------------------------------------------------
    # pure forward builders (these close over conf only — safe to jit)
    # ------------------------------------------------------------------

    def _forward_pure(
        self, params, state, x, *, train: bool, rng, upto: Optional[int] = None,
        collect: bool = False, fmask=None,
    ):
        """Forward through layers [0, upto]; returns (activation, preout
        of last executed layer, new_state, [activations]). Delegates to
        ``core.sequential_forward`` with this model's transform knobs."""
        return core.sequential_forward(
            self.conf, self.layer_names, params, state, x, train=train,
            rng=rng, upto=upto, collect=collect, fmask=fmask,
            scan_layers=self.scan_layers, remat=self.remat,
            runs=self._active_layer_runs() if self.scan_layers else (),
        )

    def _score_pure(self, params, state, x, labels, mask, rng, *,
                    train: bool, fmask=None):
        """Loss score incl. L1/L2 penalty (core.sequential_score)."""
        return core.sequential_score(
            self.conf, self.layer_names, params, state, x, labels,
            mask, rng, train=train, fmask=fmask,
            scan_layers=self.scan_layers, remat=self.remat,
            runs=self._active_layer_runs() if self.scan_layers else (),
        )

    # ------------------------------------------------------------------
    # jitted train step (built by the core)
    # ------------------------------------------------------------------

    def _score_fn(self):
        """The engine's contribution to the core step builders: a pure
        ``(params, state, x, labels, mask, fmask, rng) ->
        (score, new_state)`` closure."""
        def score_fn(p, state, x, labels, mask, fmask, rng):
            return self._score_pure(
                p, state, x, labels, mask, rng, train=True, fmask=fmask
            )
        return score_fn

    def _build_step(self) -> Callable:
        step_dtype = _dtype_of(self.conf)

        def cast(x, labels, mask, fmask):
            # on-device cast for integer-typed inputs
            return (
                x.astype(step_dtype), labels.astype(step_dtype),
                mask, fmask,
            )

        return core.build_step(
            self._score_fn(), self.updater_def, cast=cast,
            guarded=self.divergence_guard is not None,
            telemetry=self._telemetry_grad_norm,
            loss_scale=self._loss_scale_active,
            grad_accum=self.grad_accum,
            recurrent_names=self._recurrent_names(),
            zero_layout=self._zero_layout,
            stat_guard=core.stat_guard_config(self),
        )

    def set_divergence_guard(self, guard) -> None:
        """(Un)install a resilience.DivergenceGuard on the SGD train
        step (in-jit NaN/Inf suppression + host-side skip/rollback;
        with ``guard.stats`` also the statistical anomaly guard, whose
        EWMA state threads through the step). Rebuilds the jitted
        step: the guarded step returns extra outputs."""
        self.divergence_guard = guard
        self._jit_step = None
        self._jit_megastep = None

    def set_batch_validator(self, validator, quarantine=None
                            ) -> "MultiLayerNetwork":
        """(Un)install the data-plane defense (``datasets.validate``)
        on this model's ``fit`` loops."""
        core.set_batch_validator(self, validator, quarantine)
        return self

    def enable_step_telemetry(self, enabled: bool = True) -> None:
        """(Un)install step telemetry: the jitted per-step program
        additionally returns the gradient global L2 norm (one fused
        scalar — no second backward pass, no extra sync until
        something reads ``_last_grad_norm``). Rebuilds the step on
        change; observability.TelemetryListener flips this on."""
        if enabled != self._telemetry_grad_norm:
            self._telemetry_grad_norm = enabled
            self._jit_step = None
            self._jit_megastep = None

    def _multi_cast(self):
        multi_dtype = _dtype_of(self.conf)

        def cast(x, labels, mask, fmask):
            # keep the cast-on-device contract symmetric with the
            # per-step path, which converts masks to the compute dtype
            return (
                x.astype(multi_dtype), labels.astype(multi_dtype),
                None if mask is None else mask.astype(multi_dtype),
                None if fmask is None else fmask.astype(multi_dtype),
            )
        return cast

    def _recurrent_names(self) -> List[str]:
        return [
            name for name, layer in zip(self.layer_names, self.conf.layers)
            if layer.is_recurrent()
        ]

    def _build_multi_step(self) -> Callable:
        return core.build_multi_step(
            self._score_fn(), self.updater_def,
            cast=self._multi_cast(),
            recurrent_names=self._recurrent_names(),
            grad_accum=self.grad_accum,
            zero_layout=self._zero_layout,
        )

    def _build_megastep(self) -> Callable:
        """K full train steps fused into one dispatch — the multi
        step's scan discipline with the FULL per-step flavor (guard /
        telemetry / loss scale / stat guard / zero) threading through
        the carry (core.build_megastep)."""
        return core.build_megastep(
            self._score_fn(), self.updater_def,
            cast=self._multi_cast(),
            recurrent_names=self._recurrent_names(),
            guarded=self.divergence_guard is not None,
            telemetry=self._telemetry_grad_norm,
            loss_scale=self._loss_scale_active,
            stat_guard=core.stat_guard_config(self),
            grad_accum=self.grad_accum,
            zero_layout=self._zero_layout,
        )

    def _build_tbptt_multi_step(self) -> Callable:
        """TBPTT chunks fused into ONE XLA dispatch: the recurrent
        carry THREADS through the ``lax.scan`` and per-step ``resets``
        zero it at minibatch boundaries (core.build_multi_step in
        tbptt mode)."""
        return core.build_multi_step(
            self._score_fn(), self.updater_def,
            cast=self._multi_cast(),
            recurrent_names=self._recurrent_names(),
            tbptt=True,
        )

    def _can_fuse_tbptt(self, x, y, fwd: int) -> bool:
        """The fused single-dispatch TBPTT applies when chunks tile the
        sequence exactly, labels are per-timestep, every recurrent
        layer exposes an h/c streaming carry, and listeners accept
        batched iteration callbacks."""
        return (
            self.conf.iterations == 1
            and x.ndim == 3
            and x.shape[2] % fwd == 0
            and y.ndim == 3
            and y.shape[2] == x.shape[2]
            # guarded/loss-scaled runs use the per-chunk step (the
            # fused scan cannot consult either mid-dispatch)
            and self.divergence_guard is None
            and not self._loss_scale_active
            and all(
                layer.can_stream()
                and getattr(layer, "init_stream_state", None) is not None
                for layer in self.conf.layers
                if layer.is_recurrent()
            )
            and all(
                getattr(l, "supports_batched_iterations", False)
                for l in self.listeners
            )
        )

    def _stack_tbptt(self, x, y, mask, fmask):
        """Split one minibatch's device arrays into stacked TBPTT
        chunks for the fused scan: [b, n, k*fwd] -> [k, b, n, fwd]."""
        fwd = self.conf.tbptt_fwd_length
        b = x.shape[0]
        k = x.shape[2] // fwd

        def chunk3(v):
            return jnp.moveaxis(
                v.reshape(v.shape[0], v.shape[1], k, fwd), 2, 0
            )

        def chunk2(m):
            return (
                None if m is None
                else jnp.moveaxis(m.reshape(b, k, fwd), 1, 0)
            )

        resets = jnp.zeros(k, jnp.float32).at[0].set(1.0)
        return (
            chunk3(x), chunk3(y), chunk2(mask), chunk2(fmask), resets,
            k, b,
        )

    def _fit_tbptt_fused(self, x, y, mask, fmask) -> float:
        return self._run_tbptt_stacked(
            self._stack_tbptt(x, y, mask, fmask)
        )

    def _run_tbptt_stacked(self, stacked) -> float:
        xs, ys, masks, fmasks, resets, k, b = stacked
        cdt = _compute_dtype_of(self.conf)
        state = dict(self.state)
        for name, layer in zip(self.layer_names, self.conf.layers):
            if layer.is_recurrent():
                state[name] = layer.init_stream_state(b, cdt)
        it0 = self.iteration_count
        lr_stack, it0_dev = _scan_consts(self, k, it0)
        if self._jit_tbptt_multi_step is None:
            self._jit_tbptt_multi_step = self._build_tbptt_multi_step()
        (
            self.params, self.updater_state, new_state, scores,
            it0_next,
        ) = self._jit_tbptt_multi_step(
            self.params, self.updater_state, state,
            xs, ys, masks, fmasks,
            lr_stack, it0_dev, self._base_key,
            resets,
        )
        _note_it0(self, it0_next, it0 + k)
        self.state = new_state
        self.iteration_count += k
        self._last_score = scores[-1]
        if self.listeners:
            for i in range(k):
                self._last_score = scores[i]
                for listener in self.listeners:
                    listener.iteration_done(self, it0 + i + 1)
            self._last_score = scores[-1]
        self._reset_recurrent_state()
        return self._last_score

    def _can_scan_steps(self) -> bool:
        """Scan-fused fitting applies when per-minibatch semantics are
        stateless: standard backprop (recurrent carry resets each
        minibatch — the scan body restores the empty entries), not
        TBPTT (whose carry threads across host-side chunks), and
        neither divergence guard nor dynamic loss scaling is active
        (both need the per-step program). Listeners that time
        individual iterations would observe k near-simultaneous
        callbacks, so attached listeners also force the per-step path
        unless they declare ``supports_batched_iterations = True``."""
        return (
            self.conf.iterations == 1
            and self.conf.backprop
            and self.conf.backprop_type != "TruncatedBPTT"
            and self.conf.optimization_algo
            == "STOCHASTIC_GRADIENT_DESCENT"
            and self.divergence_guard is None
            and not self._loss_scale_active
            and all(
                getattr(l, "supports_batched_iterations", False)
                for l in self.listeners
            )
        )

    def _ds_scan_sig(self, ds) -> tuple:
        def sh(a):
            # np.shape, NOT np.asarray(a).shape: asarray on a device
            # array is a blocking device->host materialization (~100ms
            # through a remote tunnel) — per batch, it dwarfed the
            # training itself on the streamed-iterator path
            return None if a is None else tuple(np.shape(a))
        return (
            sh(ds.features), sh(ds.labels),
            sh(getattr(ds, "labels_mask", None)),
            sh(getattr(ds, "features_mask", None)),
        )

    def _stack_chunk(self, batches: List[Any]):
        """Stack k same-shaped minibatches into device-resident arrays
        for one fused multi-step dispatch. Integer inputs keep their
        native width (cast on device); already-device arrays stack on
        device without a host round trip."""
        dtype = _dtype_of(self.conf)

        def stack(get):
            first = get(batches[0])
            if first is None:
                return None
            return _stack_on_device([get(b) for b in batches], dtype)

        return (
            stack(lambda b: b.features),
            stack(lambda b: b.labels),
            stack(lambda b: getattr(b, "labels_mask", None)),
            stack(lambda b: getattr(b, "features_mask", None)),
            len(batches),
        )

    def _prep_prestacked(self, ds):
        """[k, b, ...] chunk payload -> the stacked device 5-tuple the
        fused dispatch drivers take (same dtype contract as
        core.stack_on_device: narrow ints ride as-is and cast on
        device; already-placed device arrays pass through)."""
        dtype = _dtype_of(self.conf)

        def prep(a):
            if a is None:
                return None
            a = a if isinstance(a, jax.Array) else jnp.asarray(a)
            return _cast_stacked(a, dtype)

        return (
            prep(ds.features), prep(ds.labels),
            prep(getattr(ds, "labels_mask", None)),
            prep(getattr(ds, "features_mask", None)), ds.k,
        )

    def _run_prestacked_chunk(self, ds) -> None:
        """One fused dispatch from a ChunkedDataSet's [k, b, ...]
        arrays."""
        k = ds.k
        if k == 1:
            from deeplearning4j_tpu.datasets.api import DataSet

            def first(a):
                return None if a is None else a[0]

            self.fit_minibatch(DataSet(
                features=first(ds.features), labels=first(ds.labels),
                features_mask=first(ds.features_mask),
                labels_mask=first(ds.labels_mask),
            ))
            return
        if self._wants_last_features():
            self._last_features = ds.features[-1]
        core.run_scan_chunk(self, self._prep_prestacked(ds))

    # ------------------------------------------------------------------
    # public API (reference fit/output/score)
    # ------------------------------------------------------------------

    def resume(self, source, load_updater: bool = True) -> int:
        """Resume training from a checkpoint: restore params, updater
        state, layer state, and the iteration/epoch counters into THIS
        model (config must match — use ``restore_model`` for a fresh
        instance). ``source`` is a resilience.CheckpointManager (newest
        restorable version, with corrupted-newest fallback) or a
        checkpoint zip path. Returns the restored step.

        Continuation is exact: per-step dropout keys fold
        ``iteration_count`` into the seed-derived base key, and lr
        schedules / updater ``t`` derive from the same counter — so
        k steps + crash + resume for N−k steps retraces the N-step
        trajectory bit-for-bit given the same data order
        (``tests/test_resilience.py``)."""
        from deeplearning4j_tpu.resilience.checkpoint import restore_into

        _, step = restore_into(self, source, load_updater=load_updater)
        return step

    def fit(self, data, labels=None, *, epochs: int = 1,
            resume_from=None, grad_accum=None,
            megastep=None) -> None:
        """fit(DataSetIterator) / fit(x, y) (reference ``fit:1048``).

        ``data`` may be a DataSetIterator-style iterable of objects with
        ``.features``/``.labels`` (and optional ``.labels_mask``), a
        single such object, or a raw (x, y) pair.

        ``resume_from``: a resilience.CheckpointManager or checkpoint
        zip path — restores params/updater/step counter before fitting
        (see ``resume``); the caller supplies the data stream from the
        restored position.

        ``grad_accum=K``: each optimizer step accumulates K microbatch
        gradients in-jit (``core.accum_grad_step``) before ONE updater
        apply — the effective batch is K× the delivered batch at one
        microbatch's activation memory. Batches must split into K equal
        microbatches; BatchNormalization configs are rejected (per-
        microbatch batch stats would change the math). The knob
        persists until changed (``grad_accum=1`` restores plain steps).

        ``megastep=K``: fold K consecutive optimizer steps (plus
        on-device metric accumulation) into ONE XLA dispatch
        (``core.build_megastep``), read back once per chunk — the
        trajectory stays bitwise equal to the per-step loop. Persists
        until changed (``megastep=1`` restores per-step dispatch);
        ineligible configs (TBPTT, recurrent, rollback guard) fall
        back to the per-step path.
        """
        from deeplearning4j_tpu.datasets.api import DataSet

        if megastep is not None:
            self.set_transforms(megastep=megastep)
        if grad_accum is not None:
            if (
                int(grad_accum) > 1
                and self.conf.backprop_type == "TruncatedBPTT"
            ):
                raise ValueError(
                    "grad_accum > 1 is incompatible with TBPTT: the "
                    "recurrent carry threads between chunks, so a "
                    "chunk cannot split into independent microbatches"
                )
            core.set_grad_accum(self, grad_accum)
        if resume_from is not None:
            self.resume(resume_from)
        if labels is not None:
            batches: Any = [DataSet(features=data, labels=labels)]
            core.fit_batches(self, batches, epochs)
            return
        if hasattr(data, "features"):
            core.fit_batches(self, [data], epochs)
            return
        core.fit_batches(self, data, epochs)

    def _fit_epochs_device_cached(self, iterator, epochs: int) -> bool:
        return core.fit_epochs_device_cached(
            self, iterator, epochs,
            lambda ds: (
                ds.features, ds.labels,
                getattr(ds, "labels_mask", None),
                getattr(ds, "features_mask", None),
            ),
            extra_plan_fn=self._tbptt_cached_plan,
        )

    def _tbptt_cached_plan(self, iterator, epochs: int):
        """HBM-resident multi-epoch plan for fused-TBPTT configs: each
        minibatch's chunk stack transfers once and replays every epoch
        through the single-dispatch TBPTT scan. Returns None (caller
        tries the standard plan / streams) when the config or data is
        ineligible."""
        if (
            epochs <= 1
            or not isinstance(iterator, (list, tuple))
            or len(iterator) == 0
            or not all(hasattr(ds, "features") for ds in iterator)
            or self.conf.backprop_type != "TruncatedBPTT"
            or self.conf.iterations != 1
            or self.conf.optimization_algo
            != "STOCHASTIC_GRADIENT_DESCENT"
            or not all(
                getattr(l, "supports_batched_iterations", False)
                for l in self.listeners
            )
        ):
            return None
        fwd = self.conf.tbptt_fwd_length
        total = 0
        for ds in iterator:
            x = np.asarray(ds.features)
            y = np.asarray(ds.labels)
            if x.ndim != 3 or x.shape[2] <= fwd or not (
                self._can_fuse_tbptt(x, y, fwd)
            ):
                return None
            for a in (
                ds.features, ds.labels,
                getattr(ds, "labels_mask", None),
                getattr(ds, "features_mask", None),
            ):
                if a is not None:
                    total += _nbytes(a)
        if total > self.device_cache_bytes:
            return None
        dtype = _dtype_of(self.conf)
        stacks = []
        for ds in iterator:
            x = _to_device(ds.features, dtype)
            y = _to_device(ds.labels, dtype)
            mask = getattr(ds, "labels_mask", None)
            fmask = getattr(ds, "features_mask", None)
            mask = None if mask is None else jnp.asarray(mask, dtype)
            fmask = None if fmask is None else jnp.asarray(fmask, dtype)
            stacks.append((self._stack_tbptt(x, y, mask, fmask), ds))
        # fuse consecutive same-shape minibatches into ONE dispatch:
        # reset flags zero the recurrent carry at each batch boundary,
        # so the whole epoch can be a single scan. Reuses the shared
        # grouping policy over (stack, ds) items.
        def merge(items):
            parts = [st for st, _ in items]
            return tuple(
                jnp.concatenate([p[i] for p in parts])
                if parts[0][i] is not None else None
                for i in range(5)
            ) + (sum(p[5] for p in parts), parts[0][6])

        grouped = _build_scan_plan(
            stacks,
            sig_fn=lambda item: tuple(
                None if a is None else a.shape for a in item[0][:4]
            ),
            stack_fn=merge,
            scan_chunk=self.scan_chunk,
        )
        return [
            ("tbptt", item[0], item[1]) if kind == "single"
            else ("tbptt", item, last[1])
            for kind, item, last in grouped
        ]

    def _step_extra_args(self) -> tuple:
        """Trailing jitted-step arguments for the active transforms
        (the dynamic loss-scale state, then the statistical guard's
        EWMA state, when engaged)."""
        extra = ()
        if self._loss_scale_active:
            extra += (core.ensure_loss_scale_state(self),)
        if core.stat_guard_active(self):
            extra += (core.ensure_stat_guard_state(self),)
        return extra

    def fit_minibatch(self, ds) -> float:
        """One minibatch through ``conf.iterations`` optimizer steps
        (reference Solver/StochasticGradientDescent.optimize; LBFGS/
        ConjugateGradient/LineGradientDescent route through
        ``optimize.solvers.Solver``)."""
        from deeplearning4j_tpu.datasets.api import ChunkedDataSet

        if isinstance(ds, ChunkedDataSet):
            # non-scan fallback: unstack and train per batch
            score = None
            for b in ds.to_datasets():
                score = self.fit_minibatch(b)
            return score
        if self.params is None:
            self.init()
        if self.conf.optimization_algo != "STOCHASTIC_GRADIENT_DESCENT":
            from deeplearning4j_tpu.optimize.solvers import (
                Solver,
                is_solver_algo,
            )

            if is_solver_algo(self.conf.optimization_algo):
                if self._solver is None:
                    self._solver = Solver(self)
                return self._solver.optimize(
                    ds.features, ds.labels,
                    mask=getattr(ds, "labels_mask", None),
                    fmask=getattr(ds, "features_mask", None),
                )
            raise ValueError(
                "Unknown optimization_algo "
                f"'{self.conf.optimization_algo}'"
            )
        if self._jit_step is None:
            self._jit_step = self._build_step()
        dtype = _dtype_of(self.conf)
        x = _to_device(ds.features, dtype)
        y = _to_device(ds.labels, dtype)
        mask = getattr(ds, "labels_mask", None)
        fmask = getattr(ds, "features_mask", None)
        if (
            self.conf.backprop_type == "TruncatedBPTT"
            and x.ndim == 3
            and x.shape[2] > self.conf.tbptt_fwd_length
        ):
            return self._fit_tbptt(x, y, mask, fmask)
        if mask is not None:
            mask = jnp.asarray(mask, dtype)
        if fmask is not None:
            fmask = jnp.asarray(fmask, dtype)
        if self._wants_last_features():
            self._last_features = ds.features  # activation listeners
        self._last_batch_rows = int(x.shape[0])  # examples/sec signal
        core.check_grad_accum_batch(self.grad_accum, int(x.shape[0]))
        prof = profiler.get_active_profiler()
        if prof is not None:
            prof.begin_step(self.iteration_count + 1)
        score = None
        for _ in range(self.conf.iterations):
            if self._jit_step is None:
                # a listener may flip telemetry/guard mid-fit (the
                # setters clear the step); rebuild before dispatch
                self._jit_step = self._build_step()
            lrs = self.updater_def.scheduled_lrs(self.iteration_count)
            t = jnp.asarray(self.iteration_count + 1, jnp.float32)
            rng = jax.random.fold_in(self._base_key, self.iteration_count)
            out = self._jit_step(
                self.params, self.updater_state, self.state,
                x, y, mask, fmask,
                {k: jnp.asarray(v, jnp.float32) for k, v in lrs.items()},
                t, rng, *self._step_extra_args(),
            )
            guard = self.divergence_guard
            score, ok = core.apply_step_out(self, out)
            self.iteration_count += 1
            self._last_score = score  # device array; sync deferred
            window = self._dispatch_window
            if window is not None:
                # async path (core.fit_batches): bounded in-flight,
                # guard flag collected guard_lag steps late — the
                # in-jit select already suppressed a bad update, so
                # the trajectory is unchanged (parallel/dispatch.py)
                window.push(score, ok)
            elif guard is not None:
                if bool(ok):  # device sync — the cost of supervision
                    guard.good_step()
                else:
                    guard.bad_step(self)
            if self.listeners:
                lt0 = time.perf_counter()
                for listener in self.listeners:
                    listener.iteration_done(self, self.iteration_count)
                if prof is not None:
                    prof.note_listener_ms(
                        (time.perf_counter() - lt0) * 1e3)
            # Reset per optimizer iteration: each pass over the same
            # minibatch starts from zero recurrent carry (also keeps
            # the step's state pytree structure stable -> no recompile)
            self._reset_recurrent_state()
        if prof is not None:
            prof.end_step(model=self, ds=ds, score=self._last_score,
                          grad_norm=getattr(self, "_last_grad_norm",
                                            None),
                          rows=self._last_batch_rows)
        return score  # 0-d device array; float() to sync

    def _wants_last_features(self) -> bool:
        """Snapshot the batch only when a listener needs it — holding a
        reference unconditionally would pin the user's feature array in
        memory for the model's lifetime."""
        return any(
            getattr(l, "needs_last_features", False)
            for l in self.listeners
        )

    def _reset_recurrent_state(self) -> None:
        """Standard-backprop mode: recurrent carry does not persist
        across minibatches (reference resets per fit call)."""
        for name, layer in zip(self.layer_names, self.conf.layers):
            if layer.is_recurrent():
                self.state[name] = {}

    def _fit_tbptt(self, x, y, mask, fmask=None) -> float:
        """Truncated BPTT: slice the time axis into fwdLen chunks and
        carry RNN state between chunks (reference
        ``doTruncatedBPTT:1210``, state carry ``:1259-1276``). The
        carry rides the layer-state pytree through the jitted step."""
        fwd = self.conf.tbptt_fwd_length
        if self._can_fuse_tbptt(x, y, fwd):
            return self._fit_tbptt_fused(x, y, mask, fmask)
        t_total = x.shape[2]
        self._reset_recurrent_state()
        score = 0.0
        for start in range(0, t_total, fwd):
            end = min(start + fwd, t_total)
            xs = x[:, :, start:end]
            ys = y[:, :, start:end] if y.ndim == 3 else y
            ms = mask[:, start:end] if mask is not None else None
            fs = fmask[:, start:end] if fmask is not None else None
            score = self._fit_chunk_with_carry(xs, ys, ms, fs)
        self._reset_recurrent_state()
        return score

    def _fit_chunk_with_carry(self, xs, ys, ms, fs=None) -> float:
        dtype = _dtype_of(self.conf)
        xs = jnp.asarray(xs, dtype)
        ys = jnp.asarray(ys, dtype)
        if ms is not None:
            ms = jnp.asarray(ms, dtype)
        if fs is not None:
            fs = jnp.asarray(fs, dtype)
        if self._jit_step is None:
            self._jit_step = self._build_step()
        self._last_batch_rows = int(xs.shape[0])  # examples/sec signal
        lrs = self.updater_def.scheduled_lrs(self.iteration_count)
        t = jnp.asarray(self.iteration_count + 1, jnp.float32)
        rng = jax.random.fold_in(self._base_key, self.iteration_count)
        out = self._jit_step(
            self.params, self.updater_state, self.state, xs, ys, ms, fs,
            {k: jnp.asarray(v, jnp.float32) for k, v in lrs.items()},
            t, rng, *self._step_extra_args(),
        )
        guard = self.divergence_guard
        score, ok = core.apply_step_out(self, out)
        self.iteration_count += 1
        self._last_score = score  # device array; sync deferred
        if guard is not None:
            if bool(ok):
                guard.good_step()
            else:
                guard.bad_step(self)
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration_count)
        return score  # 0-d device array; float() to sync

    # -- layer-wise pretraining (reference pretrain(iter) -> :166) ------

    def _input_to_layer_pure(self, params, state, x, idx):
        """Input tensor as seen by layer ``idx`` — forward through
        layers [0, idx) including idx's own preprocessor."""
        ctx = ShapeContext(
            batch=x.shape[0], time=x.shape[2] if x.ndim == 3 else -1
        )
        for i in range(idx):
            if i in self.conf.preprocessors:
                x = self.conf.preprocessors[i].preprocess(x, ctx)
            x, _ = self.conf.layers[i].apply(
                params[self.layer_names[i]], x,
                state.get(self.layer_names[i], {}), train=False, rng=None,
            )
        if idx in self.conf.preprocessors:
            x = self.conf.preprocessors[idx].preprocess(x, ctx)
        return x

    def pretrain(self, data, epochs: int = 1) -> None:
        """Greedy layer-wise unsupervised pretraining: fit each
        pretrainable layer (VAE/RBM/AutoEncoder) on the activations of
        the stack below it (reference ``pretrain(DataSetIterator)`` →
        per-layer fit at ``MultiLayerNetwork.java:166``)."""
        from deeplearning4j_tpu.datasets.api import ChunkedDataSet, DataSet

        if self.params is None:
            self.init()
        if isinstance(data, ChunkedDataSet):
            data = data.to_datasets()
        elif hasattr(data, "features"):
            data = [data]
        elif (
            isinstance(data, tuple) and len(data) == 2
            and not hasattr(data[0], "features")
        ):
            data = [DataSet(features=data[0], labels=data[1])]
        elif not isinstance(data, (list, tuple)) and not hasattr(
            data, "reset"
        ):
            # one-shot generator: materialize so every layer/epoch sees
            # the full stream (multiple passes are required)
            data = list(data)
        dtype = _dtype_of(self.conf)
        if self._jit_pretrain_input is None:
            self._jit_pretrain_input = jax.jit(
                self._input_to_layer_pure, static_argnames=("idx",)
            )
        jit_input = self._jit_pretrain_input
        for idx, (name, layer) in enumerate(
            zip(self.layer_names, self.conf.layers)
        ):
            if not layer.is_pretrainable():
                continue
            upd_def = MultiLayerUpdaterDef({name: layer.updater_settings()})
            upd_state = upd_def.init({name: self.params[name]})
            if idx not in self._jit_pretrain_steps:
                self._jit_pretrain_steps[idx] = core.build_pretrain_step(
                    layer, name, upd_def
                )
            step = self._jit_pretrain_steps[idx]
            it = 0
            for _ in range(epochs):
                for ds in _iter_unchunked(data):
                    x = jnp.asarray(
                        ds.features if hasattr(ds, "features") else ds, dtype
                    )
                    xin = jit_input(self.params, self.state, x, idx=idx)
                    for _ in range(self.conf.iterations):
                        lrs = {
                            k: jnp.asarray(v, jnp.float32)
                            for k, v in upd_def.scheduled_lrs(it).items()
                        }
                        t = jnp.asarray(it + 1, jnp.float32)
                        rng = jax.random.fold_in(
                            jax.random.fold_in(self._base_key, 7919 + idx), it
                        )
                        # reassign atomically: argnum 0 is donated
                        (
                            self.params[name], upd_state, loss,
                        ) = step(
                            self.params[name], upd_state, xin, lrs, t, rng
                        )
                        self._last_score = loss
                        it += 1
                if hasattr(data, "reset"):
                    data.reset()
        self._pretrain_done = True

    # -- inference -----------------------------------------------------

    def _output_fn(self) -> Callable:
        """The pure inference forward closure — the single source of
        truth behind both the jitted ``output`` path and the AOT
        export (identical trace -> identical executable -> bitwise
        identical results between the two)."""
        def out_fn(params, state, x, fmask, rng, train):
            out, _, _, _ = self._forward_pure(
                params, state, x, train=train, rng=rng, fmask=fmask
            )
            return out
        return out_fn

    def output(self, x, train: bool = False, features_mask=None):
        """Activated network output (reference ``output:1638``;
        ``train=True`` applies training-mode ops like dropout, and
        ``features_mask`` is the RNN input mask, reference
        ``output(INDArray,...,featuresMask,labelsMask)``)."""
        if self.params is None:
            self.init()
        dtype = _dtype_of(self.conf)
        if self._aot_outputs and not train and features_mask is None:
            # AOT-restored executable for this exact shape: same
            # program output() would have jitted, deserialized from
            # disk instead of compiled (compile/aot.py)
            fn = self._aot_outputs.get(
                tuple(int(d) for d in np.shape(x))
            )
            if fn is not None:
                return fn(self.params, self.state,
                          jnp.asarray(x, dtype))
        if self._jit_output is None:
            self._jit_output = jax.jit(
                self._output_fn(), static_argnames=("train",)
            )
        fm = (
            None if features_mask is None
            else jnp.asarray(features_mask, dtype)
        )
        rng = (
            jax.random.fold_in(self._base_key, self.iteration_count)
            if train else None
        )
        return self._jit_output(
            self.params, self.state, jnp.asarray(x, dtype), fm, rng,
            train,
        )

    # -- AOT export/install (compile/aot.py) ---------------------------

    def _output_kind(self) -> str:
        """AOT kind for the inference forward: scan-over-layers and
        Pallas kernel dispatch change the compiled program (the
        conv/dense kernels plus the eval conv->BN peephole;
        remat/loss-scale do not touch inference), so both are part of
        the artifact identity."""
        return ("output" + ("+scan" if self.scan_layers else "")
                + core.kernel_kind_suffix(self))

    def aot_fingerprint(self, shape, kind: Optional[str] = None) -> str:
        """Validity fingerprint for this model's AOT artifacts at
        ``shape``: config JSON + shape + dtype + backend + jax
        versions (see ``compile.aot.artifact_fingerprint``)."""
        from deeplearning4j_tpu.compile.aot import artifact_fingerprint

        return artifact_fingerprint(
            self.conf.to_dict(), shape,
            str(jnp.dtype(_dtype_of(self.conf))),
            kind if kind is not None else self._output_kind(),
        )

    def aot_export_output(self, x_shape, registry=None) -> bytes:
        """Serialize the compiled inference forward for inputs of
        exactly ``x_shape`` (inference mode, no mask — the serving
        bucket contract) into an AOT artifact."""
        if self.params is None:
            self.init()
        from deeplearning4j_tpu.compile.aot import export_artifact

        dtype = _dtype_of(self.conf)
        base = self._output_fn()
        fn = jax.jit(lambda p, s, xin: base(p, s, xin, None, None,
                                            False))
        spec = jax.ShapeDtypeStruct(
            tuple(int(d) for d in x_shape), dtype
        )
        return export_artifact(
            fn, (self.params, self.state, spec),
            fingerprint=self.aot_fingerprint(x_shape),
            shape=x_shape, kind=self._output_kind(),
            name=f"output-{'x'.join(str(int(d)) for d in x_shape)}",
            registry=registry,
        )

    def aot_install_output(self, x_shape, artifact,
                           registry=None) -> bool:
        """Install an inference executable for exactly ``x_shape``
        from artifact bytes (fingerprint-checked; silently refused
        and counted in ``aot_fallback_total`` when stale/corrupt) or
        a pre-loaded callable. Returns True when installed."""
        key = tuple(int(d) for d in x_shape)
        if callable(artifact):
            self._aot_outputs[key] = artifact
            return True
        from deeplearning4j_tpu.compile.aot import load_artifact

        fn = load_artifact(
            artifact,
            expected_fingerprint=self.aot_fingerprint(key),
            registry=registry,
        )
        if fn is None:
            return False
        self._aot_outputs[key] = fn
        return True

    def aot_output_shapes(self) -> List[Tuple[int, ...]]:
        """Input shapes with an installed AOT inference executable."""
        return list(self._aot_outputs)

    def aot_export_step(self, ds, registry=None) -> bytes:
        """Serialize the compiled SGD train step specialized to
        ``ds``'s feature/label shapes (no masks) — the executable a
        warm restart installs via ``aot_install_step`` to resume
        fitting without a compile. Exported fresh (never from the
        live ``_jit_step``) so guard/telemetry/transform flags at
        export time are captured in the fingerprint."""
        if self.params is None:
            self.init()
        from deeplearning4j_tpu.compile.aot import export_artifact

        # the EXACT arrays fit_minibatch would dispatch (same device
        # conversion -> same dtypes -> the executable matches)
        dtype = _dtype_of(self.conf)
        x = _to_device(ds.features, dtype)
        y = _to_device(ds.labels, dtype)
        lrs = {
            k: jnp.asarray(v, jnp.float32) for k, v in
            self.updater_def.scheduled_lrs(self.iteration_count).items()
        }
        t = jnp.asarray(1, jnp.float32)
        rng = jax.random.fold_in(self._base_key, 0)
        return export_artifact(
            self._build_step(),
            (self.params, self.updater_state, self.state, x, y,
             None, None, lrs, t, rng) + self._step_extra_args(),
            fingerprint=self.aot_fingerprint(
                x.shape, kind=self._step_kind()
            ),
            shape=x.shape, kind=self._step_kind(),
            name=f"step-{'x'.join(str(d) for d in x.shape)}",
            meta_extra={"label_shape": [int(d) for d in y.shape]},
            registry=registry,
        )

    def aot_install_step(self, artifact, registry=None) -> bool:
        """Install an AOT train-step executable as ``_jit_step``
        (dispatching to it on matching shapes, JIT otherwise — see
        ``compile.aot.AotStepFunction``). Fingerprint-checked;
        returns True when installed."""
        from deeplearning4j_tpu.compile.aot import (
            AotStepFunction,
            load_artifact,
            peek_meta,
        )

        try:
            meta = peek_meta(artifact)
            x_shape = tuple(meta["shape"])
        except Exception:
            return False
        fn = load_artifact(
            artifact,
            expected_fingerprint=self.aot_fingerprint(
                x_shape, kind=self._step_kind()
            ),
            registry=registry,
        )
        if fn is None:
            return False
        y_shape = tuple(
            meta.get("label_shape")
            or self._step_label_shape(x_shape)
        )
        self._jit_step = AotStepFunction(
            fn, x_shape, y_shape, self._build_step
        )
        return True

    def _step_kind(self) -> str:
        """AOT kind string for the train step: the guard/telemetry
        flags and the whole-net transforms change the compiled
        program (extra outputs / different HLO), so they are part of
        the artifact identity."""
        return (
            "step"
            + ("+guard" if self.divergence_guard is not None else "")
            + ("+telemetry" if self._telemetry_grad_norm else "")
            + core.transform_kind_suffix(self)
        )

    def _step_label_shape(self, x_shape) -> Tuple[int, ...]:
        """Label shape implied by the config for a feature batch of
        ``x_shape`` (n_out of the last layer; 3-d for recurrent)."""
        n_out = getattr(self.conf.layers[-1], "n_out", None)
        if len(x_shape) == 3:
            return (x_shape[0], int(n_out), x_shape[2])
        return (x_shape[0], int(n_out))

    def output_padded(self, x, n_valid, features_mask=None):
        """Inference on a row-padded batch: the serving micro-batcher
        coalesces requests, pads the stack to a shape bucket, and
        needs the first ``n_valid`` rows back bitwise identical to a
        solo ``output`` on those rows. This entry pins that contract:

        - it runs the SAME jitted forward as ``output`` (one compiled
          executable per bucket shape, shared with direct callers);
        - padding rows cannot perturb the valid rows because every
          inference-mode layer is row-independent — BatchNorm applies
          running stats, dropout is off, masks are per-row — which
          ``tests/test_batching.py`` enforces bitwise per bucket;
        - masks compose: a ``features_mask`` covering only the valid
          rows is extended with all-ones rows for the padding (an
          all-zero mask row would poison masked reductions with 0/0).
        """
        n = int(n_valid)
        b = int(np.shape(x)[0])
        if not 0 < n <= b:
            raise ValueError(
                f"n_valid must be in [1, {b}] for a {b}-row batch; "
                f"got {n}"
            )
        fm = features_mask
        if fm is not None:
            fm = np.asarray(fm)
            if fm.shape[0] == n and n < b:
                fm = np.concatenate(
                    [fm, np.ones((b - n,) + fm.shape[1:], fm.dtype)],
                    axis=0,
                )
            elif fm.shape[0] != b:
                raise ValueError(
                    f"features_mask covers {fm.shape[0]} rows; "
                    f"expected {n} (valid) or {b} (padded)"
                )
        return self.output(x, features_mask=fm)[:n]

    def feed_forward(self, x, train: bool = False) -> List[jax.Array]:
        """All per-layer activations (reference ``feedForward``)."""
        if self.params is None:
            self.init()
        rng = self._base_key if train else None
        _, _, _, acts = self._forward_pure(
            self.params, self.state, jnp.asarray(x), train=train, rng=rng,
            collect=True,
        )
        return acts

    def feed_forward_to_layer(self, layer_idx: int, x, train: bool = False):
        _, _, _, acts = self._forward_pure(
            self.params, self.state, jnp.asarray(x), train=train,
            rng=self._base_key if train else None, upto=layer_idx,
            collect=True,
        )
        return acts

    def score(self, ds=None, x=None, labels=None) -> float:
        """Loss on a dataset (reference ``score(DataSet)``)."""
        if ds is not None:
            x, labels = ds.features, ds.labels
            mask = getattr(ds, "labels_mask", None)
            fmask = getattr(ds, "features_mask", None)
        else:
            mask = fmask = None
        dtype = _dtype_of(self.conf)
        s, _ = self._score_pure(
            self.params, self.state, jnp.asarray(x, dtype),
            jnp.asarray(labels, dtype),
            jnp.asarray(mask, dtype) if mask is not None else None,
            None, train=False,
            fmask=jnp.asarray(fmask, dtype) if fmask is not None else None,
        )
        return float(s)

    # -- streaming RNN inference (reference rnnTimeStep:2290) -----------

    def rnn_time_step(self, x):
        """Feed one (or a few) timesteps, carrying streaming state
        across calls (reference ``rnnTimeStep``; state in
        ``stateMap``). Input [b, size] or [b, size, t]. Recurrent
        layers carry h/c; attention layers carry a fixed-size KV
        cache (incremental decoding — the transformer analog of the
        reference's char-RNN sampling loop)."""
        if self.params is None:
            self.init()
        for name, layer in zip(self.layer_names, self.conf.layers):
            if not layer.can_stream():
                raise ValueError(
                    f"Layer '{name}' ({type(layer).__name__}) cannot be "
                    "used with rnn_time_step — it needs the full sequence "
                    "(reference throws UnsupportedOperationException)"
                )
        dtype = _dtype_of(self.conf)
        x = jnp.asarray(x, dtype)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, :, None]
        t_new = int(x.shape[2])
        named = list(zip(self.layer_names, self.conf.layers))
        _stream_guard_and_prime(
            named, self._rnn_state, self._stream_steps, t_new,
            int(x.shape[0]), dtype,
        )
        merged = dict(self.state)
        for name, carry in self._rnn_state.items():
            merged[name] = {**merged.get(name, {}), **carry}
        if self._jit_rnn_step is None:
            def rnn_step(params, state, x):
                out, _, new_state, _ = self._forward_pure(
                    params, state, x, train=False, rng=None
                )
                return out, new_state
            self._jit_rnn_step = jax.jit(rnn_step)
        out, new_state = self._jit_rnn_step(self.params, merged, x)
        _extract_stream_state(named, new_state, self._rnn_state)
        self._stream_steps += t_new
        return out[:, :, 0] if squeeze else out

    def rnn_clear_previous_state(self) -> None:
        """Reference ``rnnClearPreviousState``."""
        self._rnn_state = {}
        self._stream_steps = 0

    def predict(self, x) -> np.ndarray:
        """Argmax class predictions (reference ``predict``)."""
        return np.asarray(jnp.argmax(self.output(x), axis=1))

    def evaluate(self, iterator):
        from deeplearning4j_tpu.datasets.api import ChunkedDataSet
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        e = Evaluation()
        for item in iterator:
            batches = (
                item.to_datasets() if isinstance(item, ChunkedDataSet)
                else [item]
            )
            for ds in batches:
                self._evaluate_one(e, ds)
        if hasattr(iterator, "reset"):
            iterator.reset()
        return e

    def _evaluate_one(self, e, ds) -> None:
        out = self.output(
            ds.features,
            features_mask=getattr(ds, "features_mask", None),
        )
        labels = np.asarray(ds.labels)
        m = getattr(ds, "labels_mask", None)
        if m is None and labels.ndim == 3:
            # per-timestep eval falls back to the features mask;
            # 2-d (per-sequence) labels must NOT — a [b, t] mask
            # cannot index b rows
            m = getattr(ds, "features_mask", None)
        e.eval(labels, np.asarray(out),
               mask=np.asarray(m) if m is not None else None)

    # -- listeners ------------------------------------------------------

    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def add_listener(self, listener) -> None:
        self.listeners.append(listener)

    # -- parameter plumbing (flat-view parity) --------------------------

    def num_params(self) -> int:
        return sum(
            int(np.prod(p.shape))
            for lp in self.params.values()
            for p in lp.values()
        )

    def _flat_order(self) -> List[Tuple[str, str]]:
        order = []
        for name, layer in zip(self.layer_names, self.conf.layers):
            pnames = list(self.params[name].keys())
            preferred = [p for p in ("W", "b") if p in pnames]
            rest = [p for p in pnames if p not in ("W", "b")]
            for pn in preferred + sorted(rest):
                order.append((name, pn))
        return order

    def params_flat(self) -> np.ndarray:
        """1-D concatenated view (reference flat params array)."""
        chunks = [
            np.asarray(self.params[ln][pn]).ravel()
            for ln, pn in self._flat_order()
        ]
        return np.concatenate(chunks) if chunks else np.zeros((0,))

    def set_params_flat(self, vec) -> None:
        vec = np.asarray(vec)
        off = 0
        for ln, pn in self._flat_order():
            p = self.params[ln][pn]
            n = int(np.prod(p.shape))
            self.params[ln][pn] = jnp.asarray(
                vec[off:off + n].reshape(p.shape), p.dtype
            )
            off += n
        if off != vec.size:
            raise ValueError(
                f"Param vector length {vec.size} != model params {off}"
            )

    def copy(self) -> "MultiLayerNetwork":
        # Deep-copy device buffers: the jitted step donates
        # params/updater-state/state, so sharing arrays between two
        # networks would let one fit() invalidate the other's buffers
        # on TPU ("Array has been deleted").
        clone = lambda a: jnp.array(a, copy=True)
        m = MultiLayerNetwork(self.conf)
        m.init(params=jax.tree_util.tree_map(clone, self.params))
        m.updater_state = jax.tree_util.tree_map(clone, self.updater_state)
        m.state = jax.tree_util.tree_map(clone, self.state)
        return m

    def summary(self) -> str:
        lines = ["=" * 70]
        lines.append(f"{'idx/name':<16}{'type':<28}{'params':>10}")
        lines.append("-" * 70)
        total = 0
        for name, layer in zip(self.layer_names, self.conf.layers):
            n = sum(
                int(np.prod(p.shape)) for p in self.params[name].values()
            ) if self.params else 0
            total += n
            lines.append(f"{name:<16}{type(layer).__name__:<28}{n:>10}")
        lines.append("-" * 70)
        lines.append(f"Total params: {total}")
        lines.append("=" * 70)
        return "\n".join(lines)
